"""Whole-step compilation (BLUEFOG_TPU_FUSED_STEP, ops/fused_step.py).

Covers the tentpole's contract surface:

  * the fused-vs-eager BITWISE state oracle on the loopback store pair:
    the same gradient stream stepped through the single jitted program
    (optimizer math + in-program per-bucket FFI puts + embedded drain)
    lands bit-identical parameters AND window state (staging rows,
    version counters, associated-P) as the eager handle-pipelined step,
    across the {none, bf16, sparse:0.5} wire codecs x {+-associated-P};
  * program-cache invalidation: a ``set_topology`` and a committed
    membership change each force a rebuild (a stale program must never
    dispatch against a new topology generation);
  * ``BLUEFOG_TPU_FUSED_STEP=0`` inertness — the default pins the eager
    path as the bitwise oracle: no program is built, no ``bf_fused_step_*``
    metric is registered;
  * graceful fallback (one warning, eager result) for a configuration
    the compiler cannot lower (per-leaf ``fuse=False`` windows).
"""

import threading
import types

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

import bluefog_tpu as bf
from bluefog_tpu import native
from bluefog_tpu import topology as topo
from bluefog_tpu.ops import fused_step as F
from bluefog_tpu.ops import transport as T
from bluefog_tpu.ops import window as W
from bluefog_tpu.ops import xlaffi
from bluefog_tpu.optim import window_optimizers as WO
from bluefog_tpu.utils import config, telemetry

needs_fused = pytest.mark.skipif(
    not (native.available() and native.has_win_xla()
         and native.has_xla_handler() and xlaffi.has_passthrough()),
    reason="native core lacks the bf_xla_win_put_pass XLA handler")


@pytest.fixture
def fused_env(monkeypatch):
    """Set knobs, reload config, and reset every xlaffi cache after."""
    def set_env(**kv):
        for k, v in kv.items():
            monkeypatch.setenv(k, str(v))
        config.reload()
        xlaffi._reset_for_tests()
    yield set_env
    config.reload()
    xlaffi._reset_for_tests()


def _params():
    """Two leaves byte-unbalanced enough that ``fusion_buckets=2`` yields
    two real buckets (two windows, two in-program puts per step)."""
    return {
        "b": jnp.asarray(np.random.RandomState(1).randn(8, 20)
                         .astype(np.float32)),
        "w": jnp.asarray(np.random.RandomState(0).randn(8, 4, 3)
                         .astype(np.float32)),
    }


def _grad_stream(params, steps, seed=42):
    rng = np.random.RandomState(seed)
    return [jax.tree.map(
        lambda x: x * 0.01 + jnp.asarray(
            rng.randn(*x.shape).astype(np.float32)) * 1e-3, params)
        for _ in range(steps)]


def _fake_distrib(transport, server_port):
    """Even ranks owned here (proc 0), odd ranks 'owned' by proc 1 whose
    endpoint is the local server transport feeding the SAME store (the
    windows were created before the directory install, so they carry
    every rank's slots) — tests/test_win_xla.py's loopback rig."""
    return W._Distrib(transport,
                      rank_owner={r: r % 2 for r in range(8)},
                      proc_addr={0: ("127.0.0.1", 1),
                                 1: ("127.0.0.1", server_port)},
                      my_proc=0)


def _run_loopback(fused_env, fused, codec, with_p, steps=4):
    """Step a 2-bucket put-family optimizer against the loopback store
    pair; returns (final params, per-window state snapshots)."""
    bf.init(lambda: topo.RingGraph(8))
    fused_env(BLUEFOG_TPU_WIN_COALESCE=1,
              BLUEFOG_TPU_WIN_COALESCE_LINGER_MS=500,
              BLUEFOG_TPU_WIN_NATIVE=1,
              BLUEFOG_TPU_WIN_XLA=1,
              BLUEFOG_TPU_WIN_COMPRESSION=codec)
    if with_p:
        bf.turn_on_win_ops_with_associated_p()
    params = _params()
    opt = WO.DistributedWinPutOptimizer(optax.sgd(0.5), fused=fused,
                                        fusion_buckets=2)
    st = opt.init(params)
    assert len(opt._names) == 2, opt._names

    applied = [0]
    cv = threading.Condition()

    def bump(k):
        with cv:
            applied[0] += k
            cv.notify_all()

    def apply(op, name, src, dst, weight, p_weight, payload):
        W._apply_inbound(op, name, src, dst, weight, p_weight, payload)
        bump(1)

    def apply_batch(msgs):
        W._apply_inbound_batch(msgs)
        bump(len(msgs))

    def apply_items(items):
        W._apply_inbound_items(items)
        bump(sum((p[5] + p[6]) if k else 1 for k, p in items))

    server = T.WindowTransport(apply, apply_batch=apply_batch,
                               apply_items=apply_items)
    client = T.WindowTransport(lambda *a: None)
    saved = W._store.distrib
    orig_update = W.win_update
    expect = [0]

    def synced_update(name, **kw):
        # Determinism gate: both legs fold the SAME arrived set — the
        # drain waits until every remote frame this step sent has been
        # applied (the loopback twin of a quiescent wire).
        with cv:
            assert cv.wait_for(lambda: applied[0] >= expect[0],
                               timeout=30), (applied[0], expect[0])
        return orig_update(name, **kw)

    try:
        assert client.native_path, "native sender required for both legs"
        for name, spl in zip(opt._names, opt._bucket_splits):
            server.register_window(name, int(spl[-1]))
        W._store.distrib = _fake_distrib(client, server.port)
        assert xlaffi.armed(), xlaffi.disarm_reason()
        W.win_update = synced_update
        p = params
        for g in _grad_stream(params, steps):
            # The (bidirectional) ring's out-edges from owned (even)
            # srcs all target odd dsts: 8 remote edges per op, per
            # bucket window.
            expect[0] += 8 * len(opt._names)
            p, st = opt.step(p, g, st, require_mutex=False)
        if fused:
            assert opt._fused_impl is not None
            assert opt._fused_impl.fused_steps == steps
            assert opt._fused_impl.builds == 1
        states = {n: bf.win_state_dict(n) for n in opt._names}
        return p, states
    finally:
        W.win_update = orig_update
        W._store.distrib = saved
        opt.free()
        client.stop()
        server.stop()
        if with_p:
            bf.turn_off_win_ops_with_associated_p()


@needs_fused
@pytest.mark.parametrize("with_p", [False, True])
@pytest.mark.parametrize("codec", ["none", "bf16", "sparse:0.5"])
def test_fused_vs_eager_loopback_state_bitwise(fused_env, codec, with_p):
    """The fused=1/0 oracle on a live wire: identical parameters and
    BIT-IDENTICAL window state whether the step ran as one XLA program
    (puts issued by data dependence inside it) or as the eager
    put/wait/update sequence, for every codec, with and without the
    associated push-sum weight."""
    pe, se = _run_loopback(fused_env, False, codec, with_p)
    pf, sf = _run_loopback(fused_env, True, codec, with_p)
    for k in pe:
        np.testing.assert_array_equal(np.asarray(pe[k]), np.asarray(pf[k]),
                                      err_msg=f"params[{k}] (bitwise)")
    for n in se:
        for part in ("staging", "versions", "p_staging", "main", "p_main"):
            assert set(se[n][part]) == set(sf[n][part]), (n, part)
            for k, v in se[n][part].items():
                np.testing.assert_array_equal(
                    np.asarray(sf[n][part][k]), np.asarray(v),
                    err_msg=f"{n}:{part}[{k}] (bitwise)")


@needs_fused
def test_program_cache_invalidation_counts(fused_env):
    """set_topology AND a committed membership change each force exactly
    one rebuild; an unchanged configuration replays the cached program."""
    bf.init(lambda: topo.RingGraph(8))
    params = _params()
    opt = WO.DistributedWinPutOptimizer(optax.sgd(0.5), fused=True,
                                        fusion_buckets=2)
    st = opt.init(params)
    try:
        p = params
        grads = _grad_stream(params, 6)
        p, st = opt.step(p, grads[0], st, require_mutex=False)
        p, st = opt.step(p, grads[1], st, require_mutex=False)
        assert opt._fused_impl.builds == 1

        # What set_topology / the elastic window rebuild do to the
        # generation counter (set_topology itself refuses while windows
        # exist; the restart-free rebuild paths bump the version with
        # the windows live — basics.py:448).
        from bluefog_tpu import basics
        basics._ctx.topology_version += 1
        p, st = opt.step(p, grads[2], st, require_mutex=False)
        assert opt._fused_impl.builds == 2, \
            "a topology generation bump must invalidate the program"

        # A committed membership change (what _maybe_churn_step lands on
        # opt.membership_change) re-keys on its epoch.
        opt.membership_change = types.SimpleNamespace(epoch=7,
                                                      evicted=False)
        p, st = opt.step(p, grads[3], st, require_mutex=False)
        assert opt._fused_impl.builds == 3, \
            "a committed membership change must invalidate the program"

        p, st = opt.step(p, grads[4], st, require_mutex=False)
        assert opt._fused_impl.builds == 3, \
            "an unchanged configuration must replay the cached program"
        assert opt._fused_impl.fused_steps == 5
    finally:
        opt.free()


def test_fused_step_env_off_is_inert(fused_env):
    """The =0 oracle's other half: with the flag off (the default) and no
    explicit fused=, the optimizer never constructs the compiler and no
    bf_fused_step_* metric appears — the eager path is untouched."""
    fused_env(BLUEFOG_TPU_FUSED_STEP=0)
    assert config.get().fused_step is False
    telemetry.reset()
    bf.init(lambda: topo.RingGraph(8))
    params = _params()
    opt = WO.DistributedWinPutOptimizer(optax.sgd(0.5), fusion_buckets=2)
    st = opt.init(params)
    try:
        p = params
        for g in _grad_stream(params, 2):
            p, st = opt.step(p, g, st, require_mutex=False)
        assert opt._fused_impl is None, "no program may be built at =0"
        snap = telemetry.snapshot()
        assert not any(k.startswith("bf_fused_step") for k in snap), \
            [k for k in snap if k.startswith("bf_fused_step")]
    finally:
        opt.free()


def test_fused_fallback_unlowerable_config_warns_once(fused_env,
                                                      monkeypatch):
    """fuse=False (per-leaf windows) cannot lower: the step falls back to
    eager with ONE warning, keeps working, and reports inactive."""
    from bluefog_tpu.utils import logging as bflog
    bf.init(lambda: topo.RingGraph(8))
    telemetry.reset()
    warns = []
    logger = bflog.get_logger()
    orig_warning = logger.warning
    monkeypatch.setattr(
        logger, "warning",
        lambda msg, *a, **kw: (warns.append(msg % a if a else msg),
                               orig_warning(msg, *a, **kw)))
    params = _params()
    opt = WO.DistributedWinPutOptimizer(optax.sgd(0.5), fused=True,
                                        fuse=False)
    st = opt.init(params)
    try:
        p = params
        for g in _grad_stream(params, 3):
            p, st = opt.step(p, g, st, require_mutex=False)
        warns = [m for m in warns
                 if "falling back to the eager path" in m]
        assert len(warns) == 1, warns
        assert opt._fused_impl is not None
        assert opt._fused_impl.fused_steps == 0
        assert telemetry.snapshot().get("bf_fused_step_active") == 0.0
    finally:
        opt.free()


def test_modeled_overlap_shape():
    """The schedule-dump preview model: bucket i's put issues at compute
    fraction (i+1)/k and overlaps the remaining (k-i-1)/k."""
    rows = F.modeled_overlap([100, 200, 300])
    assert [r["bucket"] for r in rows] == [0, 1, 2]
    assert rows[0]["overlap"] == pytest.approx(2 / 3)
    assert rows[-1]["overlap"] == 0.0
    assert rows[-1]["ready_at"] == 1.0
