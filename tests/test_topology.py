"""Topology library tests.

Case inventory mirrors the reference's ``test/torch_basics_test.py:95-126``
(static graph suite over Expo2/Ring/Star/MeshGrid) plus closed-form checks on
weights and the dynamic schedules.
"""


import numpy as np
import pytest

from bluefog_tpu import topology as topo


STATIC_SIZES = [1, 2, 3, 4, 7, 8, 12, 16]


def _check_stochastic(G):
    w = topo.weight_matrix(G)
    np.testing.assert_allclose(w.sum(axis=1), 1.0, atol=1e-12)


@pytest.mark.parametrize("size", STATIC_SIZES)
def test_exponential_two_graph(size):
    G = topo.ExponentialTwoGraph(size)
    _check_stochastic(G)
    # out-neighbors of rank 0 are exactly the powers of two < size
    expected = sorted({2 ** k for k in range(size.bit_length()) if 2 ** k < size})
    assert topo.out_neighbor_ranks(G, 0) == expected
    # circulant: every rank has the same degree
    assert topo.IsRegularGraph(G)


def test_exponential_graph_base3():
    G = topo.ExponentialGraph(10, base=3)
    assert topo.out_neighbor_ranks(G, 0) == [1, 3, 9]
    _check_stochastic(G)


def test_symmetric_exponential_graph():
    G = topo.SymmetricExponentialGraph(12, base=4)
    # offsets d where min(d, 12-d) is a power of 4: 1, 4, 8(=12-4), 11(=12-1)
    assert topo.out_neighbor_ranks(G, 0) == [1, 4, 8, 11]
    _check_stochastic(G)


@pytest.mark.parametrize("size,shape", [(4, (2, 2)), (6, (2, 3)), (12, None), (5, None)])
def test_meshgrid2d(size, shape):
    G = topo.MeshGrid2DGraph(size, shape)
    _check_stochastic(G)
    w = topo.weight_matrix(G)
    # symmetric weights => doubly stochastic => mean-preserving averaging
    np.testing.assert_allclose(w, w.T, atol=1e-12)
    np.testing.assert_allclose(w.sum(axis=0), 1.0, atol=1e-12)


def test_meshgrid2d_structure():
    G = topo.MeshGrid2DGraph(6, (2, 3))
    # corner rank 0 in a 2x3 grid: neighbors 1 (right) and 3 (below)
    assert topo.out_neighbor_ranks(G, 0) == [1, 3]
    # middle of top row, rank 1: neighbors 0, 2, 4
    assert topo.out_neighbor_ranks(G, 1) == [0, 2, 4]


@pytest.mark.parametrize("size", [2, 4, 8])
def test_star_graph(size):
    G = topo.StarGraph(size)
    _check_stochastic(G)
    w = topo.weight_matrix(G)
    np.testing.assert_allclose(w[0], 1.0 / size)   # center row uniform
    np.testing.assert_allclose(w[:, 0], 1.0 / size)
    for i in range(1, size):
        assert w[i, i] == pytest.approx(1 - 1 / size)
    assert topo.out_neighbor_ranks(G, size - 1) == [0]


def test_ring_graph_styles():
    n = 8
    bi = topo.RingGraph(n, 0)
    left = topo.RingGraph(n, 1)
    right = topo.RingGraph(n, 2)
    assert topo.out_neighbor_ranks(bi, 3) == [2, 4]
    assert topo.out_neighbor_ranks(left, 3) == [2]
    assert topo.out_neighbor_ranks(right, 3) == [4]
    for G in (bi, left, right):
        _check_stochastic(G)
    w = topo.weight_matrix(bi)
    assert w[3, 2] == pytest.approx(1 / 3)


def test_ring_tiny():
    assert topo.weight_matrix(topo.RingGraph(1)).tolist() == [[1.0]]
    np.testing.assert_allclose(topo.weight_matrix(topo.RingGraph(2)), 0.5)


def test_fully_connected():
    G = topo.FullyConnectedGraph(5)
    np.testing.assert_allclose(topo.weight_matrix(G), 0.2)


def test_equivalence():
    assert topo.IsTopologyEquivalent(topo.RingGraph(8), topo.RingGraph(8))
    assert not topo.IsTopologyEquivalent(topo.RingGraph(8), topo.RingGraph(9))
    assert not topo.IsTopologyEquivalent(topo.RingGraph(8), topo.StarGraph(8))
    assert not topo.IsTopologyEquivalent(None, topo.RingGraph(8))


def test_recv_send_weights():
    G = topo.RingGraph(6, 0)
    self_w, nbr_w = topo.GetRecvWeights(G, 2)
    assert self_w == pytest.approx(1 / 3)
    assert set(nbr_w) == {1, 3}
    assert all(v == pytest.approx(1 / 3) for v in nbr_w.values())
    self_w_s, nbr_w_s = topo.GetSendWeights(G, 2)
    assert self_w_s == pytest.approx(1 / 3)
    assert set(nbr_w_s) == {1, 3}


# --------------------------- dynamic schedules ---------------------------


def test_dynamic_one_peer_matches_phase_table():
    G = topo.ExponentialTwoGraph(8)
    phases = topo.dynamic_phase_table(G)
    gens = [topo.GetDynamicOnePeerSendRecvRanks(G, r) for r in range(8)]
    for step in range(10):
        ph = phases[step % len(phases)]
        for r in range(8):
            send, recv = next(gens[r])
            assert send == [ph.send_to[r]]
            assert sorted(recv) == sorted(ph.recv_from(r))


def test_one_peer_exp2_phases_are_shifts():
    phases = topo.one_peer_exp2_phases(8)
    assert len(phases) == 3  # offsets 1, 2, 4
    for k, ph in enumerate(phases):
        d = 2 ** k
        assert ph.send_to == tuple((i + d) % 8 for i in range(8))
        # every phase is a full permutation: everyone sends, everyone receives
        assert sorted(ph.send_to) == list(range(8))


def test_dynamic_one_peer_exp2_equals_dedicated_table():
    """On Exp2 graphs, the generic walk reduces to pure shifts."""
    G = topo.ExponentialTwoGraph(8)
    generic = topo.dynamic_phase_table(G)
    shifts = topo.one_peer_exp2_phases(8)
    assert [p.send_to for p in generic] == [p.send_to for p in shifts]


def test_exp2_machine_ranks():
    gen = topo.GetExp2DynamicSendRecvMachineRanks(
        world_size=16, local_size=4, self_rank=4, local_rank=0)
    (s0, r0), (s1, r1) = next(gen), next(gen)
    # machine 1 of 4: distances cycle 1, 2
    assert s0 == [2] and r0 == [0]
    assert s1 == [3] and r1 == [3]


def test_inner_outer_ring_consistency():
    world, local = 12, 4
    gens = [topo.GetInnerOuterRingDynamicSendRecvRanks(world, local, r)
            for r in range(world)]
    for _ in range(8):
        sends = {}
        recvs = {}
        for r in range(world):
            s, v = next(gens[r])
            sends[r] = s[0]
            recvs[r] = v[0]
        # send/recv tables must be mutually consistent permutations
        assert sorted(sends.values()) == list(range(world))
        for r in range(world):
            assert recvs[sends[r]] == r


def test_inner_outer_expo2_consistency():
    world, local = 32, 8
    gens = [topo.GetInnerOuterExpo2DynamicSendRecvRanks(world, local, r)
            for r in range(world)]
    for _ in range(16):
        sends = {}
        recvs = {}
        for r in range(world):
            s, v = next(gens[r])
            sends[r] = s[0]
            recvs[r] = v[0]
        assert sorted(sends.values()) == list(range(world))
        for r in range(world):
            assert recvs[sends[r]] == r


def test_phase_table_period_lcm():
    # ring: everyone degree 2 -> period 2; star: center degree n-1, leaves 1
    assert len(topo.dynamic_phase_table(topo.RingGraph(6, 0))) == 2
    assert len(topo.dynamic_phase_table(topo.StarGraph(5))) == 4


def test_weight_matrix_roundtrip():
    w = topo.weight_matrix(topo.MeshGrid2DGraph(6))
    G2 = topo.from_weight_matrix(w)
    assert topo.IsTopologyEquivalent(topo.MeshGrid2DGraph(6), G2)


def test_pod_scale_phase_table_n128():
    """Pod-scale (v4-128) schedule compilation, validated virtually: the
    one-peer Exp2 phase table at n=128 compiles to exactly log2(n) = 7
    one-ppermute phases, every phase is a permutation (column-stochastic
    with 0.5/0.5 weights), and the 7-phase product mixes to EXACT uniform
    consensus (0.5**7 == 1/128 is exact in binary floating point).
    Nothing here needs 128 chips — the schedule and its mixing math are
    device-count-free numpy."""
    import numpy as np
    from bluefog_tpu.ops import schedule as S
    n = 128
    phases = topo.one_peer_exp2_phases(n)
    assert len(phases) == 7
    for k, ph in enumerate(phases):
        send = np.asarray(ph.send_to)
        assert sorted(send) == list(range(n))  # a permutation: one peer each
        np.testing.assert_array_equal(send, (np.arange(n) + 2 ** k) % n)
    dyn = S.compile_dynamic(phases, n)
    assert dyn.period == 7
    W = np.eye(n)
    for ph in dyn.phases:
        assert len(ph.rounds) == 1  # one ppermute per phase
        M = np.diag(ph.self_scale.astype(np.float64))
        rnd = ph.rounds[0]
        for s, d in rnd.pairs:
            M[s, d] = rnd.send_scale[s]
        np.testing.assert_array_equal(M.sum(axis=0), 1.0)  # column-stochastic
        np.testing.assert_array_equal(M.sum(axis=1), 1.0)  # row-stochastic
        W = W @ M
    np.testing.assert_array_equal(W, np.full((n, n), 1.0 / n))
    # The static Exp2 compiles to the same 7 shift classes as one program.
    st = S.compile_static(topo.ExponentialTwoGraph(n), use_topo_weights=False)
    assert len(st.rounds) == 7
