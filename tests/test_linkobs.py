"""Link observatory (utils/linkobs.py), SLO engine, linkdelay chaos
fault, trace-gossip --json and the tools-top dashboard.

Covers the tentpole's contract surface:
  * BLUEFOG_TPU_LINK_OBS=0 => bitwise inert: no note_* site mutates the
    registry or the module state, on_step never evaluates;
  * delay/jitter EWMA math, min-normalized measured-vs-modeled
    divergence, and the bf_link_* gauge surface;
  * the SLO grammar (good/bad specs, the metric vocabulary), breach
    latch + bf_slo_breaches_total + degraded /healthz links block +
    recovery;
  * report_from_snapshot / merge_link_snapshots purity and cross-rank
    agreement (the bf.link_report() claim, collective-free);
  * churn/shutdown hygiene: clear_edges / clear_peer / clear_all retire
    every published series;
  * the linkdelay fault: spec parse defaults + ChaosInjector engage/heal
    + the transport sleeping on DATA ops only;
  * tools trace-gossip --json round-trip (json.loads, same edges as the
    text table) and tools top parse/render (pure frame).
"""

import json

import numpy as np
import pytest

from bluefog_tpu.ops import transport as T
from bluefog_tpu.tools import tracegossip
from bluefog_tpu.tools import top as topmod
from bluefog_tpu.utils import chaos as uchaos
from bluefog_tpu.utils import config, flightrec, linkobs, telemetry


@pytest.fixture
def link_env(monkeypatch):
    """Set knobs + reload config; linkobs and the registry start and end
    clean."""
    def set_env(**kv):
        for k, v in kv.items():
            if v is None:
                monkeypatch.delenv(k, raising=False)
            else:
                monkeypatch.setenv(k, str(v))
        config.reload()
    telemetry.reset()
    linkobs.reset()
    yield set_env
    linkobs.reset()
    telemetry.reset()
    config.reload()


def _link_series():
    return {k: v for k, v in telemetry.snapshot().items()
            if k.startswith(("bf_link_", "bf_slo_"))}


# ---------------------------------------------------------------------------
# Off-switch: bitwise inert
# ---------------------------------------------------------------------------

def test_link_obs_off_is_inert(link_env):
    link_env(BLUEFOG_TPU_LINK_OBS="0",
             BLUEFOG_TPU_SLO="link_delay_us>=1")
    assert not linkobs.enabled()
    now_us = 1_000_000
    linkobs.note_commit(1, 0, (1, 7, 0, now_us - 5_000, 3))
    linkobs.note_delay(2, 0, 60000.0)
    linkobs.note_tx("h:1", 0, 1e6)
    linkobs.on_step(5)
    assert telemetry.snapshot() == {}
    assert not linkobs._edges and not linkobs._tx
    # The armed rule never evaluated: nothing latched, no counter.
    assert linkobs.slo_state() == {"rules": [], "breached": {}}
    assert linkobs.health_summary() is None


def test_link_obs_on_by_default(link_env):
    link_env(BLUEFOG_TPU_LINK_OBS=None)
    assert linkobs.enabled()


# ---------------------------------------------------------------------------
# Estimator math: EWMA, jitter, divergence
# ---------------------------------------------------------------------------

def test_delay_ewma_and_gauges(link_env):
    link_env()
    for _ in range(40):
        linkobs.note_delay(3, 0, 60000.0)
    snap = telemetry.snapshot()
    # 0.8^39 ~ 1.7e-4: fully converged on the injected delay.
    assert snap['bf_link_delay_us{dst="0",src="3"}'] == \
        pytest.approx(60000.0, rel=0.01)
    # Constant samples -> jitter decays toward 0.
    assert snap['bf_link_jitter_us{dst="0",src="3"}'] < 1000.0
    assert any(k.startswith("bf_link_delay_seconds_bucket") for k in snap)
    pct = telemetry.histogram_percentiles(
        "bf_link_delay_seconds", qs=(50.0,), src="3", dst="0")
    assert pct is not None and 0.01 < pct[50.0] < 0.1


def test_divergence_min_normalized(link_env):
    """One slow edge against uniform predictions reads ~k x the fastest
    edge; healthy edges sit at ~1.0 (no placement model here => uniform
    predicted cost)."""
    link_env()
    for _ in range(40):
        linkobs.note_delay(1, 0, 500.0)
        linkobs.note_delay(2, 0, 520.0)
        linkobs.note_delay(3, 0, 60000.0)
    snap = telemetry.snapshot()
    hot = snap['bf_link_divergence_ratio{dst="0",src="3"}']
    assert hot > linkobs.DIVERGENCE_ALERT
    assert hot == pytest.approx(120.0, rel=0.1)
    assert snap['bf_link_divergence_ratio{dst="0",src="1"}'] == \
        pytest.approx(1.0, rel=0.1)


def test_goodput_window(link_env, monkeypatch):
    import time as _time
    link_env()
    monkeypatch.setattr(linkobs, "_GOODPUT_WINDOW_S", 0.001)
    linkobs.note_tx("h:9", 1, 1000.0)
    _time.sleep(0.005)
    linkobs.note_tx("h:9", 1, 1000.0)  # second call closes the window
    snap = telemetry.snapshot()
    keys = [k for k in snap
            if k.startswith("bf_link_goodput_bytes") and 'peer="h:9"' in k]
    assert keys and snap[keys[0]] > 0


# ---------------------------------------------------------------------------
# SLO grammar + engine
# ---------------------------------------------------------------------------

def test_slo_parse_good():
    rules = linkobs.parse_slo_rules(
        "link_delay_us>=50000; step_lag>128 ;bf_win_tx_queue_depth<=900")
    assert [(r.metric, r.op, r.threshold) for r in rules] == [
        ("link_delay_us", ">=", 50000.0),
        ("step_lag", ">", 128.0),
        ("bf_win_tx_queue_depth", "<=", 900.0)]
    assert linkobs.parse_slo_rules(None) == []
    assert linkobs.parse_slo_rules("  ;  ") == []
    r = linkobs.parse_slo_rules("goodput_bytes<1e6")[0]
    assert r.threshold == 1e6 and r.check(5e5) and not r.check(2e6)


@pytest.mark.parametrize("bad", [
    "link_delay_us=5",          # not a comparison op
    "nonsense>5",               # unknown metric, not bf_*
    "link_delay_us>",           # missing value
    ">=5",                      # missing metric
    "link_delay_us>five",
])
def test_slo_parse_bad_fails_loudly(bad):
    with pytest.raises(ValueError, match="BLUEFOG_TPU_SLO"):
        linkobs.parse_slo_rules(bad)


def test_slo_malformed_spec_fails_at_config_load(link_env, monkeypatch):
    monkeypatch.setenv("BLUEFOG_TPU_SLO", "what even is this")
    with pytest.raises(ValueError, match="BLUEFOG_TPU_SLO"):
        config.reload()
    # Un-break the env BEFORE fixture teardown reloads config again.
    monkeypatch.delenv("BLUEFOG_TPU_SLO")
    config.reload()


def test_slo_breach_latch_healthz_and_recovery(link_env):
    link_env(BLUEFOG_TPU_SLO="link_delay_us>=20000;link_jitter_us>=1e9",
             BLUEFOG_TPU_FLIGHT_RECORDER="0")
    for _ in range(10):
        linkobs.note_delay(2, 0, 500.0)
    linkobs.on_step(1)
    st = linkobs.slo_state()
    assert st["rules"] == ["link_delay_us>=20000", "link_jitter_us>=1e9"]
    assert st["breached"] == {}
    hz = linkobs.health_summary()
    assert hz["slo"]["breached"] == []
    # Drive the delay over the threshold: exactly the matching rule
    # latches; the quiet rule stays quiet.
    for _ in range(40):
        linkobs.note_delay(2, 0, 60000.0)
    linkobs.on_step(2)
    st = linkobs.slo_state()
    assert list(st["breached"]) == ["link_delay_us>=20000"]
    assert st["breached"]["link_delay_us>=20000"] >= 20000.0
    snap = telemetry.snapshot()
    assert snap[
        'bf_slo_breaches_total{rule="link_delay_us>=20000"}'] == 1.0
    hz = linkobs.health_summary()
    assert hz["slo"]["breached"] == ["link_delay_us>=20000"]
    assert hz["worst_edge"] == "2->0"
    # The telemetry /healthz body degrades on the latched breach.
    body = telemetry.health()
    assert body["links"]["slo"]["breached"] and \
        body["status"] == "degraded"
    # Re-evaluating while still breached must NOT re-count (latched).
    linkobs.on_step(3)
    assert telemetry.snapshot()[
        'bf_slo_breaches_total{rule="link_delay_us>=20000"}'] == 1.0
    # Recovery: EWMA back under threshold -> latch clears, health green.
    for _ in range(60):
        linkobs.note_delay(2, 0, 100.0)
    linkobs.on_step(4)
    assert linkobs.slo_state()["breached"] == {}
    assert telemetry.health()["status"] in ("ok", "stalled")


def test_slo_no_signal_never_breaches(link_env):
    link_env(BLUEFOG_TPU_SLO="link_delay_us>=1;goodput_bytes<=1e12")
    linkobs.on_step(1)  # no edges, no tx: value None on both rules
    assert linkobs.slo_state()["breached"] == {}


# ---------------------------------------------------------------------------
# Snapshot purity: merge + report, cross-rank agreement
# ---------------------------------------------------------------------------

def _rank_snapshot(edges):
    """Build one rank's rendered bf_link_* snapshot via the real
    ingestion path, then reset for the next 'rank'."""
    for (src, dst), us in edges.items():
        for _ in range(40):
            linkobs.note_delay(src, dst, us)
    snap = _link_series()
    linkobs.reset()
    telemetry.reset()
    return snap


def test_merge_and_report_cross_rank_agreement(link_env):
    """Each edge lives on its receiver; the gauge-MAX merge of per-rank
    snapshots is the SAME matrix no matter who computes it — the chaos
    rig's collective-free stand-in for bf.link_report()."""
    link_env()
    s0 = _rank_snapshot({(3, 0): 60000.0, (1, 0): 400.0})
    s1 = _rank_snapshot({(3, 1): 58000.0, (2, 1): 380.0})
    s2 = _rank_snapshot({(0, 2): 410.0, (1, 2): 395.0})
    reports = [linkobs.report_from_snapshot(
        linkobs.merge_link_snapshots(order))
        for order in ([s0, s1, s2], [s2, s0, s1], [s1, s2, s0])]
    assert reports[0] == reports[1] == reports[2]
    rep = reports[0]
    assert rep["hot_edge"]["src"] == 3 and rep["hot_edge"]["dst"] == 0
    assert rep["hot_edge"]["delay_us"] == pytest.approx(60000, rel=0.01)
    assert len(rep["edges"]) == 6
    assert rep["max_divergence_ratio"] > linkobs.DIVERGENCE_ALERT
    # Purity: assembling a report never touches the live registry.
    assert telemetry.snapshot() == {}


def test_merge_ignores_non_link_series(link_env):
    link_env()
    merged = linkobs.merge_link_snapshots([
        {'bf_link_delay_us{dst="0",src="1"}': 5.0,
         "bf_async_step_lag": 99.0},
        {'bf_link_delay_us{dst="0",src="1"}': 7.0}])
    assert merged == {'bf_link_delay_us{dst="0",src="1"}': 7.0}


# ---------------------------------------------------------------------------
# Hygiene: churn eviction, peer drop, shutdown
# ---------------------------------------------------------------------------

def test_clear_edges_churn_hygiene(link_env):
    link_env()
    for src in (1, 3, 5):
        linkobs.note_delay(src, 0, 500.0)
    linkobs.clear_edges([3])
    snap = telemetry.snapshot()
    assert 'bf_link_delay_us{dst="0",src="3"}' not in snap
    assert 'bf_link_divergence_ratio{dst="0",src="3"}' not in snap
    assert 'bf_link_delay_us{dst="0",src="1"}' in snap
    assert (3, 0) not in linkobs._edges and (1, 0) in linkobs._edges


def test_clear_peer_and_clear_all(link_env, monkeypatch):
    link_env()
    import time as _time
    monkeypatch.setattr(linkobs, "_GOODPUT_WINDOW_S", 0.001)
    linkobs.note_tx("h:1", 0, 1000.0)
    linkobs.note_tx("h:2", 1, 1000.0)
    _time.sleep(0.005)
    linkobs.note_tx("h:1", 0, 1000.0)
    linkobs.note_tx("h:2", 1, 1000.0)
    linkobs.note_delay(1, 0, 500.0)
    linkobs.clear_peer("h:1")
    snap = telemetry.snapshot()
    assert not any('peer="h:1"' in k for k in snap)
    assert any('peer="h:2"' in k for k in snap)
    linkobs.clear_all()
    # Every GAUGE is retired; the cumulative delay histogram persists
    # (histograms are monotone scrape series, not live claims).
    left = [k for k in _link_series()
            if not k.startswith("bf_link_delay_seconds")]
    assert left == []
    # Hygiene runs even when the observatory is OFF (teardown contract).
    link_env(BLUEFOG_TPU_LINK_OBS="0")
    linkobs.clear_edges([1])
    linkobs.clear_peer("h:2")
    linkobs.clear_all()


# ---------------------------------------------------------------------------
# linkdelay fault: spec, injector, transport
# ---------------------------------------------------------------------------

def test_linkdelay_spec_parse_defaults():
    f = uchaos.parse_chaos("linkdelay:rank=3:step=40")[0]
    assert (f.kind, f.rank, f.step, f.steps, f.ms) == \
        ("linkdelay", 3, 40, 10, 60.0)
    f = uchaos.parse_chaos("linkdelay:rank=1:step=5:steps=7:ms=25")[0]
    assert (f.steps, f.ms) == (7, 25.0)
    assert f.active_at(5) and f.active_at(11) and not f.active_at(12)
    with pytest.raises(ValueError):
        uchaos.parse_chaos("linkdelay:rank=1")     # step missing
    with pytest.raises(ValueError):
        uchaos.parse_chaos("linkdelay:rank=1:step=2:bogus=3")


class _FakeTransport:
    def __init__(self):
        self.delays = []

    def set_send_delay(self, seconds):
        self.delays.append(seconds)


def test_chaos_injector_linkdelay_engage_heal():
    faults = uchaos.parse_chaos(
        "linkdelay:rank=3:step=10:steps=3:ms=50,"
        "linkdelay:rank=2:step=11:steps=1:ms=80")
    tr = _FakeTransport()
    inj = uchaos.ChaosInjector([2, 3], faults=faults, transport=tr)
    inj.apply(9)
    assert tr.delays == []             # not engaged yet
    inj.apply(10)
    assert tr.delays == [0.05]         # rank-3 fault engages
    inj.apply(11)
    assert tr.delays == [0.05, 0.08]   # overlapping faults: the MAX
    inj.apply(12)
    assert tr.delays == [0.05, 0.08, 0.05]
    inj.apply(13)
    assert tr.delays[-1] == 0.0        # healed exactly once
    inj.apply(14)
    assert len(tr.delays) == 4         # no repeat calls while steady


def test_chaos_injector_ignores_other_ranks():
    faults = uchaos.parse_chaos("linkdelay:rank=3:step=1:steps=5:ms=50")
    tr = _FakeTransport()
    inj = uchaos.ChaosInjector([0, 1], faults=faults, transport=tr)
    for s in range(8):
        inj.apply(s)
    assert tr.delays == []


def test_transport_send_delay_data_ops_only(link_env):
    """set_send_delay sleeps DATA sends only — heartbeats/fences ride
    undelayed, so churn suspicion stays quiet during a linkdelay
    fault."""
    import time as _time
    link_env(BLUEFOG_TPU_WIN_COALESCE_LINGER_MS="2")
    got = []
    import threading
    cv = threading.Condition()

    def apply(op, name, src, dst, weight, p_weight, payload):
        with cv:
            got.append(op & ~T.OP_FLAG_MASK)
            cv.notify_all()

    def apply_batch(msgs):
        for m in msgs:
            apply(*m)

    server = T.WindowTransport(apply, apply_batch=apply_batch)
    client = T.WindowTransport(lambda *a: None)
    try:
        client.set_send_delay(0.15)
        row = np.zeros(4, np.float32)
        t0 = _time.perf_counter()
        client.send("127.0.0.1", server.port, T.OP_PUT, "w", 0, 1, 1.0,
                    row)
        client.flush()
        with cv:
            assert cv.wait_for(lambda: T.OP_PUT in got, timeout=30)
        assert _time.perf_counter() - t0 >= 0.15   # the data op slept
        client.set_send_delay(0.0)
        t0 = _time.perf_counter()
        client.send("127.0.0.1", server.port, T.OP_PUT, "w", 1, 1, 1.0,
                    row)
        client.flush()
        with cv:
            assert cv.wait_for(lambda: got.count(T.OP_PUT) >= 2,
                               timeout=30)
        assert _time.perf_counter() - t0 < 0.15    # healed: no sleep
    finally:
        client.stop()
        server.stop()


# ---------------------------------------------------------------------------
# trace-gossip --json round-trip
# ---------------------------------------------------------------------------

def _write_fake_dump(path, rank, unix_us, mono_us, events):
    arr = np.zeros(len(events), flightrec.EVENT_DTYPE)
    for i, e in enumerate(events):
        for k, v in e.items():
            arr[i][k] = v
    with open(path, "wb") as f:
        f.write(flightrec.HEADER.pack(flightrec.MAGIC, flightrec.VERSION,
                                      rank, 0, unix_us, mono_us,
                                      len(arr)))
        f.write(arr.tobytes())


def _fake_two_rank_prefix(tmp_path):
    prefix = str(tmp_path / "flightrec")
    _write_fake_dump(
        f"{prefix}.0.bin", 0, unix_us=10_000_000, mono_us=0,
        events=[dict(t_us=1_000, src=0, dst=1, seq=5, len=64,
                     etype=flightrec.ENQUEUE, op=T.OP_PUT, name=b"w")])
    _write_fake_dump(
        f"{prefix}.1.bin", 1, unix_us=10_000_000, mono_us=500_000,
        events=[dict(t_us=501_250, src=0, dst=1, seq=5, len=64,
                     etype=flightrec.DECODE,
                     op=T.OP_PUT | T.OP_TRACE_FLAG, name=b"w")])
    return prefix


def test_trace_gossip_json_roundtrip(tmp_path, capsys):
    prefix = _fake_two_rank_prefix(tmp_path)
    rc = tracegossip.main_trace_gossip(prefix, as_json=True)
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)   # ONE json object
    assert set(payload) >= {"trace", "stats", "edges"}
    assert payload["stats"]["flows_matched"] == 1
    assert payload["edges"] == [{"src": 0, "dst": 1, "tags": 1,
                                 "p50_ms": 0.25, "p99_ms": 0.25,
                                 "max_ms": 0.25}]
    # Same edges as the text table renders.
    dumps = tracegossip.load_dumps(prefix)
    table = tracegossip.delay_table(tracegossip.edge_delays(dumps))
    for row in payload["edges"]:
        assert f"{row['src']} -> {row['dst']}" in table


def test_trace_gossip_text_mode_unchanged(tmp_path, capsys):
    prefix = _fake_two_rank_prefix(tmp_path)
    rc = tracegossip.main_trace_gossip(prefix)
    assert rc == 0
    out = capsys.readouterr().out
    assert "0 -> 1" in out
    with pytest.raises(json.JSONDecodeError):
        json.loads(out)   # text mode is NOT the json contract


# ---------------------------------------------------------------------------
# tools top: parse + pure frame render
# ---------------------------------------------------------------------------

def test_top_parse_prometheus():
    text = ("# HELP bf_x whatever\n"
            "bf_async_step_lag 3\n"
            'bf_link_delay_us{dst="0",src="3"} 60000.0\n'
            "garbage-line-no-value\n"
            "bf_bad notanumber\n")
    m = topmod.parse_prometheus(text)
    assert m == {"bf_async_step_lag": 3.0,
                 'bf_link_delay_us{dst="0",src="3"}': 60000.0}


def test_top_render_frame_matrix_and_down(link_env):
    link_env()
    metrics = {
        'bf_link_delay_us{dst="0",src="3"}': 60000.0,
        'bf_link_delay_us{dst="0",src="1"}': 400.0,
        'bf_link_jitter_us{dst="0",src="3"}': 900.0,
        'bf_link_divergence_ratio{dst="0",src="3"}': 150.0,
        "bf_async_step_lag": 2.0,
    }
    health = {"status": "degraded",
              "async": {"step": 41, "step_lag": 2},
              "links": {"slo": {"rules": ["link_delay_us>=20000"],
                                "breached": ["link_delay_us>=20000"]}}}
    frame = topmod.render_frame({"h:9100": (metrics, health),
                                 "h:9101": (None, None)})
    assert "1/2 endpoint(s) up" in frame
    assert "DOWN" in frame                       # dead endpoint row
    assert "3 -> 0" in frame and "<- HOT" in frame
    # The per-rank slo column truncates at 20 chars.
    assert "BREACH link_delay_us" in frame
    assert "degraded" in frame


def test_top_render_frame_empty_matrix(link_env):
    link_env()
    frame = topmod.render_frame({"h:9100": ({}, {"status": "ok"})})
    assert "no bf_link_* series yet" in frame


def test_top_endpoint_discovery_explicit():
    class A:
        endpoints = "h1:9100, h2:9101"
        gang_dir = None
    assert topmod._discover_endpoints(A()) == ["h1:9100", "h2:9101"]
    with pytest.raises(SystemExit):
        class B:
            endpoints = None
            gang_dir = None
        topmod._discover_endpoints(B())
