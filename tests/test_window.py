"""One-sided window op tests.

Case inventory mirrors reference ``test/torch_win_ops_test.py``: create/sync/
free (:64), update with weights (:141-244), put/get/accumulate with given
destinations (:245-704), versions (:286,575), mutex semantics (:705-779), and
the randomized associated-P push-sum invariant (:780-863).
"""

import numpy as np
import pytest

import bluefog_tpu as bf
from bluefog_tpu import topology as topo

N = 8


def setup_ring():
    bf.init(lambda: topo.RingGraph(N))  # bidirectional ring: indeg 2


def rank_major(seed=0, shape=(N, 5)):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


def test_win_create_update_free():
    setup_ring()
    x = rank_major()
    assert bf.win_create(x, "w")
    assert not bf.win_create(x, "w")  # duplicate name
    assert bf.get_current_created_window_names() == ["w"]
    out = np.asarray(bf.win_update("w"))
    # Fresh window, no puts: staging holds neighbors' initial values, so
    # update = uniform neighbor average of the initial tensors.
    expect = np.stack([
        (x[r] + x[(r - 1) % N] + x[(r + 1) % N]) / 3.0 for r in range(N)])
    np.testing.assert_allclose(out, expect, rtol=1e-5)
    assert bf.win_free("w")
    assert bf.get_current_created_window_names() == []
    assert not bf.win_free("w")


def test_set_topology_fails_with_windows():
    """Reference: basics.py set_topology refuses while windows exist
    (``torch_basics_test.py:63-93``)."""
    setup_ring()
    bf.win_create(rank_major(), "w")
    with pytest.raises(RuntimeError, match="windows exist"):
        bf.set_topology(topo.ExponentialGraph(N))
    bf.win_free("w")
    assert bf.set_topology(topo.ExponentialGraph(N))


def test_win_put_then_update():
    setup_ring()
    x = rank_major(1)
    bf.win_create(x, "w", zero_init=True)
    two = 2.0 * x
    bf.win_put(two, "w")  # every rank pushes 2x to its out-neighbors
    out = np.asarray(bf.win_update("w", self_weight=0.5,
                                   neighbor_weights={(r, s): 0.25
                                                     for r in range(N)
                                                     for s in [(r - 1) % N,
                                                               (r + 1) % N]}))
    expect = np.stack([
        0.5 * x[r] + 0.25 * two[(r - 1) % N] + 0.25 * two[(r + 1) % N]
        for r in range(N)])
    np.testing.assert_allclose(out, expect, rtol=1e-5)
    bf.win_free()


def test_win_put_partial_destinations():
    """dst_weights dict restricts and scales destinations
    (reference 'given destinations' cases)."""
    setup_ring()
    x = np.ones((N, 3), np.float32)
    bf.win_create(x, "w", zero_init=True)
    # Each rank sends only clockwise (to rank+1), weight 0.5.
    dst = {((r), (r + 1) % N): 0.5 for r in range(N)}
    bf.win_put(x, "w", dst_weights=dst)
    out = np.asarray(bf.win_update("w", self_weight=1.0,
                                   neighbor_weights={(r, s): 1.0
                                                     for r in range(N)
                                                     for s in [(r - 1) % N,
                                                               (r + 1) % N]}))
    # self (1.0) + 0.5 from counter-clockwise neighbor + 0 from clockwise.
    np.testing.assert_allclose(out, np.full((N, 3), 1.5), rtol=1e-5)
    bf.win_free()


def test_win_update_partial_weights_leaves_excluded_edges_pending():
    """An edge excluded from an explicit partial ``neighbor_weights`` keeps
    its staged mass AND its staleness counter for the next update —
    reference resets only the buffers included in neighbor_weights
    (``torch/mpi_ops.py`` win_update doc)."""
    setup_ring()
    x = np.ones((N, 3), np.float32)
    bf.win_create(x, "w", zero_init=True)
    bf.win_put(x, "w")  # both in-edges of every rank staged, versions = 1
    # Consume only the counter-clockwise edge (src = r-1).
    ccw = {(r, (r - 1) % N): 1.0 for r in range(N)}
    out = np.asarray(bf.win_update("w", self_weight=1.0,
                                   neighbor_weights=ccw, reset_weights=True))
    np.testing.assert_allclose(out, np.full((N, 3), 2.0), rtol=1e-5)
    # Excluded clockwise edge: version counter untouched, mass pending.
    assert bf.get_win_version("w", 0) == {(N - 1): 0, 1: 1}
    full = {(r, s): 1.0 for r in range(N)
            for s in [(r - 1) % N, (r + 1) % N]}
    out2 = np.asarray(bf.win_update("w", self_weight=1.0,
                                    neighbor_weights=full,
                                    reset_weights=True))
    # Consumed edge was reset to zero; excluded edge still held its put.
    np.testing.assert_allclose(out2, np.full((N, 3), 3.0), rtol=1e-5)
    assert bf.get_win_version("w", 0) == {(N - 1): 0, 1: 0}
    bf.win_free()


def test_win_accumulate():
    setup_ring()
    x = np.ones((N, 2), np.float32)
    bf.win_create(x, "w", zero_init=True)
    bf.win_accumulate(x, "w")
    bf.win_accumulate(x, "w")  # staging for each in-edge now holds 2.0
    out = np.asarray(bf.win_update("w", self_weight=1.0,
                                   neighbor_weights={(r, s): 1.0
                                                     for r in range(N)
                                                     for s in [(r - 1) % N,
                                                               (r + 1) % N]}))
    np.testing.assert_allclose(out, np.full((N, 2), 1.0 + 2.0 + 2.0),
                               rtol=1e-5)
    bf.win_free()


def test_win_get():
    setup_ring()
    x = rank_major(2)
    bf.win_create(x, "w", zero_init=True)
    bf.win_get("w", src_weights={(r, s): 0.5 for r in range(N)
                                 for s in [(r - 1) % N, (r + 1) % N]})
    out = np.asarray(bf.win_update("w", self_weight=1.0,
                                   neighbor_weights={(r, s): 1.0
                                                     for r in range(N)
                                                     for s in [(r - 1) % N,
                                                               (r + 1) % N]}))
    expect = np.stack([
        x[r] + 0.5 * x[(r - 1) % N] + 0.5 * x[(r + 1) % N] for r in range(N)])
    np.testing.assert_allclose(out, expect, rtol=1e-5)
    bf.win_free()


def test_win_versions():
    setup_ring()
    x = rank_major(3)
    bf.win_create(x, "w")
    assert bf.get_win_version("w", 0) == {(N - 1): 0, 1: 0}
    bf.win_put(x, "w")
    assert bf.get_win_version("w", 0) == {(N - 1): 1, 1: 1}
    bf.win_put(x, "w")
    assert bf.get_win_version("w", 0) == {(N - 1): 2, 1: 2}
    bf.win_update("w")  # resets staleness counters
    assert bf.get_win_version("w", 0) == {(N - 1): 0, 1: 0}
    bf.win_free()


def test_win_mutex_excludes_writers():
    """Holding a rank's mutex blocks require_mutex puts to it until release
    (reference ``test_win_mutex_full:705``)."""
    import threading
    import time
    setup_ring()
    x = np.ones((N, 2), np.float32)
    bf.win_create(x, "w", zero_init=True)
    progressed = threading.Event()

    def writer():
        bf.win_put(x, "w", require_mutex=True)
        progressed.set()

    with bf.win_mutex("w", ranks=list(range(N))):
        t = threading.Thread(target=writer)
        t.start()
        time.sleep(0.15)
        assert not progressed.is_set(), "put proceeded despite held mutex"
    t.join(timeout=5)
    assert progressed.is_set()
    bf.win_free()


def test_associated_p_push_sum_invariant():
    """Randomized push-sum: after K column-stochastic accumulate+collect
    rounds, sum(p) == n and x/p converges to the initial average
    (reference ``torch_win_ops_test.py:780-863``)."""
    bf.init(lambda: topo.RingGraph(N, connect_style=2))  # ring, send to i+1
    bf.turn_on_win_ops_with_associated_p()
    try:
        x = rank_major(4, (N, 3))
        target = x.mean(axis=0)
        bf.win_create(x, "w", zero_init=True)
        cur = x.copy()
        self_share = 0.5  # directed ring: 1 out-neighbor
        # Directed-ring mixing rate is |0.5 + 0.5 e^{2pi i/8}| ~= 0.92, so
        # ~150 rounds reach 1e-5 consensus error.
        for _ in range(150):
            bf.win_accumulate(
                cur, "w", self_weight=self_share,
                dst_weights={(r, (r + 1) % N): 0.5 for r in range(N)})
            cur = np.asarray(bf.win_update_then_collect("w"))
            p = np.asarray(bf.win_associated_p("w"))
            assert abs(p.sum() - N) < 1e-6, "P mass not conserved"
        debiased = cur / p[:, None]
        np.testing.assert_allclose(
            debiased, np.tile(target, (N, 1)), rtol=1e-3, atol=1e-3)
    finally:
        bf.turn_off_win_ops_with_associated_p()
        bf.win_free()


# ---------------------------------------------------------------------------
# Multi-process windows over the DCN transport
# ---------------------------------------------------------------------------

_MULTIPROC_SCRIPT = r"""
import os, sys
sys.path.insert(0, "@REPO@")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import bluefog_tpu as bf
from bluefog_tpu import topology as topo

bf.init_distributed()
assert jax.process_count() > 1, jax.process_count()
n = bf.size(); assert n == int(os.environ.get("BFTPU_EXPECT_RANKS", "4")), n
bf.set_topology(topo.RingGraph(n))  # bidirectional ring: indeg 2
owned = [i for i, d in enumerate(jax.devices())
         if d.process_index == jax.process_index()]
x = (np.arange(n, dtype=np.float32)[:, None] + 1.0).repeat(3, 1)  # row r=r+1

# put across processes: fence certifies remote applies, versions count edges
assert bf.win_create(x, "w", zero_init=True)
bf.win_put(2.0 * x, "w")
bf.win_fence()
for r in owned:
    v = bf.get_win_version("w", r)
    assert set(v) == {(r - 1) % n, (r + 1) % n}, v
    assert all(c == 1 for c in v.values()), v
u = np.asarray(bf.win_update("w"))
main = x.copy()
for r in range(n):
    main[r] = (x[r] + 2.0 * x[(r - 1) % n] + 2.0 * x[(r + 1) % n]) / 3.0
for r in owned:
    np.testing.assert_allclose(u[r], main[r], rtol=1e-5)
    assert all(c == 0 for c in bf.get_win_version("w", r).values())
bf.barrier()  # peers must not start the next phase's one-sided traffic
              # until every process finished asserting this phase's state

# accumulate across processes (two adds on top of the prior put's staging)
bf.win_accumulate(x, "w")
bf.win_accumulate(x, "w")
bf.win_fence()
u2 = np.asarray(bf.win_update("w"))
prev = main.copy()
for r in range(n):
    main[r] = (prev[r] + 4.0 * x[(r - 1) % n] + 4.0 * x[(r + 1) % n]) / 3.0
for r in owned:
    np.testing.assert_allclose(u2[r], main[r], rtol=1e-5)
bf.barrier()

# one-sided pull from a remote owner's authoritative memory
bf.win_get("w")
bf.win_fence()
u3 = np.asarray(bf.win_update("w"))
for r in owned:
    expect = (main[r] + main[(r - 1) % n] + main[(r + 1) % n]) / 3.0
    np.testing.assert_allclose(u3[r], expect, rtol=1e-5)
bf.barrier()

# cross-process mutex: both processes lock a remote rank concurrently
remote = next(r for r in range(n) if r not in owned)
with bf.win_mutex("w", ranks=[remote]):
    pass
bf.win_fence()
bf.win_free("w")

# push-sum across processes: associated-P de-bias reaches consensus
bf.turn_on_win_ops_with_associated_p()
bf.set_topology(topo.RingGraph(n, connect_style=2))  # directed: send to r+1
y = np.random.RandomState(7).randn(n, 3).astype(np.float32)
target = y.mean(axis=0)
bf.win_create(y, "ps", zero_init=True)
cur = y.copy()
for _ in range(20 * n):  # directed-ring mixing slows with n
    bf.win_accumulate(cur, "ps", self_weight=0.5,
                      dst_weights={(r, (r + 1) % n): 0.5 for r in range(n)})
    bf.win_fence()
    cur = np.asarray(bf.win_update_then_collect("ps"))
p = np.asarray(bf.win_associated_p("ps"))
for r in owned:
    np.testing.assert_allclose(cur[r] / p[r], target, rtol=1e-3, atol=1e-3)
bf.turn_off_win_ops_with_associated_p()
bf.win_free("ps")
print("MULTIPROC-WIN-OK", jax.process_index())
"""


def test_payload_row_bf16_wire_flag():
    """bf16 compression is declared by the OP_BF16_FLAG wire bit, never
    inferred from the payload size; size mismatches are rejected loudly."""
    import jax.numpy as jnp
    from bluefog_tpu.ops import window as W
    bf.init()
    bf.set_topology(topo.RingGraph(bf.size()))
    x = np.random.RandomState(0).randn(bf.size(), 6).astype(np.float32)
    assert bf.win_create(x, "pw")
    win = W._store.get("pw")
    row = x[1]
    plain = W._payload_row(win, row.tobytes(), compressed=False)
    np.testing.assert_array_equal(plain, row)
    comp = W._payload_row(win, row.astype(jnp.bfloat16).tobytes(),
                          compressed=True)
    np.testing.assert_allclose(comp, row, rtol=1e-2)
    assert comp.dtype == np.float32
    # A half-length payload WITHOUT the flag is an error, not silent bf16.
    with pytest.raises(ValueError):
        W._payload_row(win, row.astype(jnp.bfloat16).tobytes(),
                       compressed=False)
    # A full-length payload WITH the flag is likewise rejected.
    with pytest.raises(ValueError):
        W._payload_row(win, row.tobytes(), compressed=True)
    bf.win_free("pw")


_COMPRESS_SCRIPT = r"""
import os, sys
sys.path.insert(0, "@REPO@")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import bluefog_tpu as bf
from bluefog_tpu import topology as topo

bf.init_distributed()
n = bf.size()
bf.set_topology(topo.RingGraph(n))
owned = [i for i, d in enumerate(jax.devices())
         if d.process_index == jax.process_index()]
x = (np.arange(n, dtype=np.float32)[:, None] + 1.0).repeat(3, 1)
assert bf.win_create(x, "w", zero_init=True)
bf.win_put(2.0 * x, "w")
bf.win_fence()
u = np.asarray(bf.win_update("w"))
main = x.copy()
for r in range(n):
    main[r] = (x[r] + 2.0 * x[(r - 1) % n] + 2.0 * x[(r + 1) % n]) / 3.0
for r in owned:
    np.testing.assert_allclose(u[r], main[r], rtol=1e-2)  # bf16 edges
print("COMPRESSED-WIN-OK", jax.process_index())
"""


@pytest.mark.slow
def test_multiprocess_windows_bf16_compression(tmp_path):
    """Cross-process window gossip with BLUEFOG_TPU_WIN_COMPRESSION=bf16:
    half the DCN bytes, results correct to bf16 tolerance."""
    import os
    import subprocess
    import sys
    from bluefog_tpu import native
    if not native.available():
        pytest.skip("native transport not built")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "win_compress.py"
    script.write_text(_COMPRESS_SCRIPT.replace("@REPO@", repo))
    env = dict(os.environ, BLUEFOG_TPU_WIN_COMPRESSION="bf16")
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.run", "-np", "2",
         "--devices-per-proc", "2", sys.executable, str(script)],
        capture_output=True, text=True, timeout=600, cwd=repo, env=env)
    assert out.returncode == 0, f"stdout={out.stdout}\nstderr={out.stderr}"
    assert out.stdout.count("COMPRESSED-WIN-OK") == 2, out.stdout


@pytest.mark.slow
@pytest.mark.parametrize("n_proc,devs_per_proc", [(2, 2), (4, 2)])
def test_multiprocess_windows(tmp_path, n_proc, devs_per_proc):
    """The one-sided family over the DCN TCP transport reproduces the
    single-process oracles on owned ranks (VERDICT round-1 missing #1) —
    at 2x2 (4 ranks) and 4x2 (8 ranks, each process owning a minority)."""
    import os
    import subprocess
    import sys
    from bluefog_tpu import native
    if not native.available():
        pytest.skip("native transport not built")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "win_multiproc.py"
    script.write_text(_MULTIPROC_SCRIPT.replace("@REPO@", repo))
    env = dict(os.environ,
               BFTPU_EXPECT_RANKS=str(n_proc * devs_per_proc))
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.run", "-np", str(n_proc),
         "--devices-per-proc", str(devs_per_proc), sys.executable,
         str(script)],
        capture_output=True, text=True, timeout=900, cwd=repo, env=env)
    assert out.returncode == 0, f"stdout={out.stdout}\nstderr={out.stderr}"
    # processes share stdout; lines can interleave — count occurrences
    assert out.stdout.count("MULTIPROC-WIN-OK") == n_proc, out.stdout


def test_win_state_dict_resume_bit_exact(tmp_path):
    """Checkpoint/restore of a window mid-push-sum: the resumed run must
    reproduce the uninterrupted run bit-exactly (staging mass, versions and
    associated-P all survive the round trip, incl. an orbax round trip)."""
    from bluefog_tpu.utils import checkpoint

    def fresh(seed=5):
        bf.init(lambda: topo.RingGraph(8, connect_style=2))  # send to r+1
        x = np.random.RandomState(seed).randn(8, 4).astype(np.float32)
        bf.turn_on_win_ops_with_associated_p()
        assert bf.win_create(x, "ck", zero_init=True)
        return x

    def gossip_step(cur):
        bf.win_accumulate(cur, "ck", self_weight=0.5,
                          dst_weights={(r, (r + 1) % 8): 0.5
                                       for r in range(8)})
        return np.asarray(bf.win_update_then_collect("ck"))

    # Uninterrupted run: 6 steps.
    cur = fresh()
    for _ in range(3):
        cur = gossip_step(cur)
    snap = bf.win_state_dict("ck")
    mid = cur.copy()
    for _ in range(3):
        cur = gossip_step(cur)
    final_ref = cur.copy()
    p_ref = np.asarray(bf.win_associated_p("ck")).copy()
    bf.win_free("ck")
    bf.shutdown()

    # Orbax round trip of the snapshot.
    path = checkpoint.save(str(tmp_path / "win"), snap)
    snap_back = checkpoint.restore(path)

    # Fresh context, restore, replay the last 3 steps.
    fresh()
    bf.win_load_state_dict("ck", snap_back)
    cur = mid
    for _ in range(3):
        cur = gossip_step(cur)
    np.testing.assert_array_equal(cur, final_ref)
    np.testing.assert_array_equal(
        np.asarray(bf.win_associated_p("ck")), p_ref)
    bf.win_free("ck")


def test_win_load_state_dict_validates():
    bf.init(lambda: topo.RingGraph(8))
    x = np.zeros((8, 3), np.float32)
    bf.win_create(x, "v")
    snap = bf.win_state_dict("v")
    bf.win_free("v")
    bf.win_create(np.zeros((8, 5), np.float32), "v")  # different shape
    with pytest.raises(ValueError, match="does not match"):
        bf.win_load_state_dict("v", snap)
    bf.win_free("v")


def test_owned_slice_allocation_is_o_owned_plus_indegree():
    """_Window allocates ONLY owned rows and their in-edges: at n=64
    virtual ranks owning one, per-window state is O(owned + indeg) — not
    O(n) rank-major buffers plus an O(n^2) version matrix (round-3 VERDICT
    Weak #4)."""
    from bluefog_tpu.ops.window import _Window
    n = 64
    ring_in = [[(r - 1) % n, (r + 1) % n] for r in range(n)]
    ring_out = ring_in
    t = np.zeros((1, 1000), np.float32)  # owned-rows tensor: one rank
    w = _Window("big", t, ring_in, ring_out, zero_init=True,
                owned=[3], layout="owned")
    assert set(w.main) == {3}
    assert set(w.staging) == {(3, 2), (3, 4)}
    assert set(w.versions) == set(w.staging)
    assert set(w.p_staging) == set(w.staging)
    assert set(w.mutexes) == {3} and set(w.main_versions) == {3}
    assert set(w.p_main) == {3}
    assert w.row_of[3] == 0
    # Rank layout at single-process (owns all): full state, same dict form.
    t_all = np.zeros((n, 4), np.float32)
    w2 = _Window("all", t_all, ring_in, ring_out, zero_init=False,
                 owned=list(range(n)), layout="rank")
    assert len(w2.main) == n and len(w2.staging) == 2 * n
    # Owned layout cannot seed staging from neighbor rows it doesn't have.
    with pytest.raises(ValueError, match="zero_init"):
        _Window("bad", t, ring_in, ring_out, zero_init=False,
                owned=[3], layout="owned")


_OWNED_LAYOUT_SCRIPT = r"""
import sys
sys.path.insert(0, "@REPO@")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import bluefog_tpu as bf
from bluefog_tpu import topology as topo

bf.init_distributed()
n = bf.size()
owned = bf.owned_ranks()
bf.set_topology(topo.RingGraph(n))
k = len(owned)

# Owned-rows layout: (k, ...) arrays, row i = owned[i]; O(n) buffers never
# materialize.  Oracle: same put/update as the rank-major layout.
x_own = np.stack([np.full(3, r, np.float32) for r in owned])
assert bf.win_create(x_own, "ow", zero_init=True)
from bluefog_tpu.ops import window as W
win = W._store.get("ow")
assert win.layout == "owned" and len(win.main) == k, (win.layout, len(win.main))

bf.win_put(2.0 * x_own, "ow")  # push 2*rank to out-neighbors
bf.win_fence()
out = np.asarray(bf.win_update("ow", self_weight=1.0,
                               neighbor_weights={(r, s): 1.0
                                                 for r in range(n)
                                                 for s in [(r - 1) % n,
                                                           (r + 1) % n]}))
assert out.shape == (k, 3), out.shape
for i, r in enumerate(owned):
    expect = r + 2.0 * ((r - 1) % n) + 2.0 * ((r + 1) % n)
    np.testing.assert_allclose(out[i], np.full(3, expect), rtol=1e-5)

# Rank-major payloads on an owned-layout window are rejected loudly.
try:
    bf.win_put(np.zeros((n, 3), np.float32), "ow")
    raise SystemExit("rank-major payload accepted on owned-layout window")
except ValueError as e:
    assert "owned-rows" in str(e), e
bf.win_free("ow")
print("OWNED-LAYOUT-OK", jax.process_index(), flush=True)
"""


@pytest.mark.slow
@pytest.mark.parametrize("n_proc,devs_per_proc", [(2, 2), (4, 2)])
def test_owned_layout_multiprocess(tmp_path, n_proc, devs_per_proc):
    """The owned-rows window layout over the real transport: (owned, ...)
    payloads in, (owned, ...) combines out, same gossip math as the
    rank-major oracle."""
    import os
    import subprocess
    import sys
    from bluefog_tpu import native
    if not native.available():
        pytest.skip("native transport not built")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "owned.py"
    script.write_text(_OWNED_LAYOUT_SCRIPT.replace("@REPO@", repo))
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.run", "-np", str(n_proc),
         "--devices-per-proc", str(devs_per_proc), sys.executable,
         str(script)],
        capture_output=True, text=True, timeout=600, cwd=repo,
        env={**os.environ})
    assert out.returncode == 0, \
        f"stdout={out.stdout}\nstderr={out.stderr[-4000:]}"
    assert out.stdout.count("OWNED-LAYOUT-OK") == n_proc, out.stdout
