"""Cross-rank gossip tracing (OP_TRACE_FLAG wire tags, the native flight
recorder, per-edge contribution-age telemetry, and the trace-gossip
merge tool).

Covers the tentpole's contract surface:
  * trailer round-trip + sampling semantics (`BLUEFOG_TPU_TRACE_SAMPLE`);
  * SAMPLE off => the wire is bitwise identical to the untraced
    transport AND nothing in the tracing machinery mutates;
  * the tag survives OP_BATCH framing x bf16/sparse codecs x 1/2/4
    stripes, with the native and Python decode paths cross-checked
    against each other (same committed state, bitwise) and against the
    untraced run (the tag must never perturb numerics);
  * per-edge age histograms + freshest/stalest gauges, /healthz block,
    gauge clearing (churn hygiene), TELEMETRY=0 zero-mutation;
  * flight-recorder struct pinning, dump/load round-trip, and the
    fake-clock two-rank trace-gossip merge (flow arrows + one-way-delay
    math).
"""

import ctypes
import struct
import threading

import numpy as np
import pytest

import bluefog_tpu as bf
from bluefog_tpu import native
from bluefog_tpu import topology as topo
from bluefog_tpu.ops import transport as T
from bluefog_tpu.ops import window as W
from bluefog_tpu.tools import tracegossip
from bluefog_tpu.utils import config, flightrec, telemetry

needs_native = pytest.mark.skipif(
    not (native.available() and native.has_win_native()),
    reason="native core lacks the window-transport hot path")
needs_xla = pytest.mark.skipif(
    not (native.available() and native.has_win_xla()),
    reason="native core lacks the bf_xla symbols")


@pytest.fixture
def trace_env(monkeypatch):
    """Set knobs + reload config; restores (and reloads) afterwards."""
    def set_env(**kv):
        for k, v in kv.items():
            if v is None:
                monkeypatch.delenv(k, raising=False)
            else:
                monkeypatch.setenv(k, str(v))
        config.reload()
    yield set_env
    config.reload()


@pytest.fixture(autouse=True)
def _reset_trace_counters():
    """Each test starts with fresh Python-side sampling counters and a
    clean per-edge age table."""
    with T._trace_lock:
        T._trace_count = 0
        T._trace_seq = 0
    W.clear_contribution_age()
    yield
    W.clear_contribution_age()


# ---------------------------------------------------------------------------
# Trailer + sampling semantics
# ---------------------------------------------------------------------------

def test_trailer_roundtrip_and_sampling(trace_env):
    trace_env(BLUEFOG_TPU_TRACE_SAMPLE="1/3")
    tags = [T.make_trace_tag(src=7) for _ in range(9)]
    hits = [t for t in tags if t is not None]
    assert len(hits) == 3 and tags[0] is not None  # every 3rd, from #1
    body = b"\x01\x02\x03\x04"
    stripped, tag = T.trace_strip(body + hits[0])
    assert bytes(stripped) == body
    src, seq, mono, unix, step = tag
    assert src == 7 and seq == 1 and mono > 0 and unix > mono  # unix >> mono
    assert step == -1  # no step clock published in this test
    # Sequences are unique and monotonic across samples.
    seqs = [T.TRACE_TRAILER.unpack(t)[1] for t in hits]
    assert seqs == [1, 2, 3]


def test_trace_strip_rejects_short_payload():
    with pytest.raises(ValueError, match="trailer"):
        T.trace_strip(b"\x00" * (T.TRACE_TRAILER.size - 1))


def test_sample_off_is_inert(trace_env):
    """Default (unset): no tag, no counter mutation — the zero-overhead
    contract behind the bitwise-identical-wire guarantee."""
    trace_env(BLUEFOG_TPU_TRACE_SAMPLE=None)
    before = (T._trace_count, T._trace_seq)
    assert all(T.make_trace_tag(0) is None for _ in range(100))
    assert (T._trace_count, T._trace_seq) == before
    trace_env(BLUEFOG_TPU_TRACE_SAMPLE="0")
    assert T.make_trace_tag(0) is None


def test_trace_sample_parse():
    assert config._parse_trace_sample(None) == 0
    assert config._parse_trace_sample("0") == 0
    assert config._parse_trace_sample("off") == 0
    assert config._parse_trace_sample("1/64") == 64
    assert config._parse_trace_sample("64") == 64
    assert config._parse_trace_sample("1/1") == 1
    with pytest.raises(ValueError):
        config._parse_trace_sample("every-now-and-then")
    with pytest.raises(ValueError):
        config._parse_trace_sample("-3")


# ---------------------------------------------------------------------------
# Wire equivalence: SAMPLE off => bitwise identical frames
# ---------------------------------------------------------------------------

@needs_native
@pytest.mark.parametrize("win_native", ["0", "1"])
def test_wire_bitwise_identical_with_sample_off(trace_env, win_native):
    """With BLUEFOG_TPU_TRACE_SAMPLE unset, every delivered message is
    byte-for-byte what the untraced transport ships: no OP_TRACE_FLAG,
    payload exactly the row — on both the Python and native senders."""
    trace_env(BLUEFOG_TPU_TRACE_SAMPLE=None,
              BLUEFOG_TPU_WIN_NATIVE=win_native,
              BLUEFOG_TPU_WIN_COALESCE_LINGER_MS="2")
    got = []
    cv = threading.Condition()

    def apply(op, name, src, dst, weight, p_weight, payload):
        with cv:
            got.append((op, name, src, dst, weight, bytes(payload)))
            cv.notify_all()

    def apply_batch(msgs):
        for m in msgs:
            apply(*m)

    server = T.WindowTransport(apply, apply_batch=apply_batch)
    client = T.WindowTransport(lambda *a: None)
    try:
        expect = []
        for i in range(12):
            row = (np.arange(8, dtype=np.float32) * (i + 1))
            client.send("127.0.0.1", server.port, T.OP_PUT, "w", i % 4, 1,
                        0.5, row)
            expect.append((T.OP_PUT, "w", i % 4, 1, 0.5, row.tobytes()))
        client.flush()
        with cv:
            assert cv.wait_for(lambda: len(got) >= len(expect), timeout=30)
        assert sorted(got) == sorted(expect)  # stripes may interleave
        assert all((op & T.OP_TRACE_FLAG) == 0 for op, *_ in got)
    finally:
        client.stop()
        server.stop()


# ---------------------------------------------------------------------------
# Loopback-through-store: tag survives framing x codecs x stripes
# ---------------------------------------------------------------------------

def _drive_store(trace_env, *, sample, win_native, codec="none",
                 stripes=1, server_native=None):
    """One deterministic put/accumulate stream through the real window-op
    path into a loopback store; returns (state, age_series).

    ``server_native`` lets the two wire ends run DIFFERENT hot paths
    (native-encoded frames decoded by the Python decoder and vice
    versa) — the cross-codec check of the tentpole."""
    bf.init(lambda: topo.RingGraph(8))
    if server_native is None:
        server_native = win_native
    trace_env(BLUEFOG_TPU_WIN_COALESCE="1",
              BLUEFOG_TPU_WIN_COALESCE_LINGER_MS="300",
              BLUEFOG_TPU_WIN_NATIVE=server_native,
              BLUEFOG_TPU_WIN_XLA="0",
              BLUEFOG_TPU_WIN_STRIPES=str(stripes),
              BLUEFOG_TPU_WIN_COMPRESSION=codec,
              BLUEFOG_TPU_TRACE_SAMPLE=sample)
    with T._trace_lock:
        T._trace_count = 0
        T._trace_seq = 0
    telemetry.reset()
    W.clear_contribution_age()
    applied = [0]
    cv = threading.Condition()

    def bump(k):
        with cv:
            applied[0] += k
            cv.notify_all()

    def apply(op, name, src, dst, weight, p_weight, payload):
        W._apply_inbound(op, name, src, dst, weight, p_weight, payload)
        bump(1)

    def apply_batch(msgs):
        W._apply_inbound_batch(msgs)
        bump(len(msgs))

    def apply_items(items):
        W._apply_inbound_items(items)
        bump(sum((p[5] + p[6]) if k else 1 for k, p in items))

    server = T.WindowTransport(apply, apply_batch=apply_batch,
                               apply_items=apply_items)
    trace_env(BLUEFOG_TPU_WIN_NATIVE=win_native)  # client side's path
    client = T.WindowTransport(lambda *a: None)
    saved = W._store.distrib
    rng = np.random.RandomState(11)
    try:
        assert bf.win_create(rng.randn(8, 6).astype(np.float32), "trace",
                             zero_init=True)
        server.register_window("trace", 6)
        W._store.distrib = W._Distrib(
            client, rank_owner={r: r % 2 for r in range(8)},
            proc_addr={0: ("127.0.0.1", 1),
                       1: ("127.0.0.1", server.port)},
            my_proc=0)
        total = 0
        for step in range(6):
            t = np.random.RandomState(500 + step) \
                .randn(8, 6).astype(np.float32)
            if step % 2:
                bf.win_accumulate(t, "trace")
            else:
                bf.win_put(t, "trace")
            total += 8  # the ring's 8 remote (even->odd) edges per op
            with cv:
                assert cv.wait_for(lambda: applied[0] >= total,
                                   timeout=30), (applied[0], total)
        state = bf.win_state_dict("trace")
        ages = {k: v for k, v in telemetry.snapshot().items()
                if k.startswith("bf_win_contribution")}
        return state, ages
    finally:
        W._store.distrib = saved
        bf.win_free("trace")
        client.stop()
        server.stop()


def _assert_state_equal(a, b, what):
    for part in ("staging", "versions", "main"):
        assert set(a[part]) == set(b[part]), (what, part)
        for k, v in a[part].items():
            np.testing.assert_array_equal(
                np.asarray(b[part][k]), np.asarray(v),
                err_msg=f"{what}: {part}[{k}] (bitwise)")


@needs_native
@pytest.mark.parametrize("codec", ["none", "bf16", "sparse:0.5"])
@pytest.mark.parametrize("stripes", [1, 2, 4])
def test_tag_survives_framing_property(trace_env, codec, stripes):
    """The tentpole property: a 1/1-sampled stream commits BIT-identical
    window state to the untraced stream across OP_BATCH framing x codec
    x stripe count on the native path — the trailer is stripped exactly,
    never decoded as payload — and the age telemetry appears per src."""
    traced, ages = _drive_store(trace_env, sample="1", win_native="1",
                                codec=codec, stripes=stripes)
    plain, no_ages = _drive_store(trace_env, sample=None, win_native="1",
                                  codec=codec, stripes=stripes)
    _assert_state_equal(plain, traced, f"{codec} x{stripes}")
    assert any(k.startswith("bf_win_contribution_age_seconds_bucket")
               for k in ages), sorted(ages)[:5]
    assert any("freshest" in k for k in ages)
    assert not no_ages  # untraced run records no age series


@needs_native
@pytest.mark.parametrize("codec", ["none", "bf16", "sparse:0.5"])
def test_native_python_decoder_cross_check(trace_env, codec):
    """Native-encoded tagged frames decoded by the PYTHON drain (and the
    python-encoded ones by the native drain) land the same committed
    state as the all-python leg — the two codecs agree on every byte of
    the trailer handling."""
    py, _ = _drive_store(trace_env, sample="1", win_native="0",
                         codec=codec)
    nat_tx, _ = _drive_store(trace_env, sample="1", win_native="1",
                             codec=codec, server_native="0")
    py_tx, _ = _drive_store(trace_env, sample="1", win_native="0",
                            codec=codec, server_native="1")
    _assert_state_equal(py, nat_tx, f"native-tx/{codec}")
    _assert_state_equal(py, py_tx, f"native-rx/{codec}")


@needs_xla
def test_xla_plan_encoder_tags(trace_env):
    """The THIRD encoder — the zero-copy XLA put plans (bf_trace_next in
    C) — tags sampled device-array puts identically: committed state
    stays bitwise equal to the untraced plan run, ages are recorded, and
    the native sequence space (bit 31) never collides with Python's."""
    import jax.numpy as jnp

    from bluefog_tpu.ops import xlaffi

    def drive(sample):
        bf.init(lambda: topo.RingGraph(8))
        trace_env(BLUEFOG_TPU_WIN_COALESCE="1",
                  BLUEFOG_TPU_WIN_COALESCE_LINGER_MS="300",
                  BLUEFOG_TPU_WIN_NATIVE="1",
                  BLUEFOG_TPU_WIN_XLA="1",
                  BLUEFOG_TPU_WIN_STRIPES="1",
                  BLUEFOG_TPU_WIN_COMPRESSION="none",
                  BLUEFOG_TPU_TRACE_SAMPLE=sample,
                  BLUEFOG_TPU_FLIGHT_RECORDER="1")
        xlaffi._reset_for_tests()
        telemetry.reset()
        W.clear_contribution_age()
        applied = [0]
        cv = threading.Condition()

        def bump(k):
            with cv:
                applied[0] += k
                cv.notify_all()

        def apply(op, name, src, dst, weight, p_weight, payload):
            W._apply_inbound(op, name, src, dst, weight, p_weight, payload)
            bump(1)

        def apply_items(items):
            W._apply_inbound_items(items)
            bump(sum((p[5] + p[6]) if k else 1 for k, p in items))

        server = T.WindowTransport(apply, apply_items=apply_items)
        client = T.WindowTransport(lambda *a: None)
        flightrec.reset()
        saved = W._store.distrib
        rng = np.random.RandomState(19)
        try:
            assert bf.win_create(rng.randn(8, 5).astype(np.float32),
                                 "xtr", zero_init=True)
            server.register_window("xtr", 5)
            W._store.distrib = W._Distrib(
                client, rank_owner={r: r % 2 for r in range(8)},
                proc_addr={0: ("127.0.0.1", 1),
                           1: ("127.0.0.1", server.port)},
                my_proc=0)
            if not xlaffi.armed():
                pytest.skip(f"xla path disarmed: "
                            f"{xlaffi.disarm_reason()}")
            total = 0
            for step in range(4):
                t = jnp.asarray(np.random.RandomState(700 + step)
                                .randn(8, 5).astype(np.float32))
                bf.win_put(t, "xtr")
                total += 8
                with cv:
                    assert cv.wait_for(lambda: applied[0] >= total,
                                       timeout=30), (applied[0], total)
            snap = telemetry.snapshot()
            assert any(k.startswith("bf_win_xla_puts_total")
                       for k in snap), "plan path did not engage"
            ages = {k: v for k, v in snap.items()
                    if k.startswith("bf_win_contribution")}
            return bf.win_state_dict("xtr"), ages, flightrec.snapshot()
        finally:
            W._store.distrib = saved
            bf.win_free("xtr")
            client.stop()
            server.stop()

    traced, ages, ev = drive("1")
    plain, no_ages, _ = drive(None)
    _assert_state_equal(plain, traced, "xla-plan traced")
    assert any(k.startswith("bf_win_contribution_age_seconds_bucket")
               for k in ages), sorted(ages)[:5]
    assert not no_ages
    # Native-encoder sequence space: bit 31 set on every plan-path tag.
    dec = ev[ev["etype"] == flightrec.DECODE]
    assert len(dec) > 0
    assert np.all(dec["seq"].astype(np.int64) & 0x80000000)


# ---------------------------------------------------------------------------
# Age telemetry + churn hygiene + zero mutation
# ---------------------------------------------------------------------------

def test_contribution_age_math_and_healthz(trace_env):
    trace_env(BLUEFOG_TPU_TELEMETRY="1")
    telemetry.reset()
    import time
    now_us = time.time_ns() // 1000
    # Two samples for src 3: ~2 s old and ~0.5 s old.
    W._note_trace_commit("w", 3, (3, 1, 0, now_us - 2_000_000))
    W._note_trace_commit("w", 3, (3, 2, 0, now_us - 500_000))
    pct = telemetry.histogram_percentiles(
        "bf_win_contribution_age_seconds", qs=(50.0,), src="3")
    assert pct is not None and 0.2 < pct[50.0] < 5.0
    snap = telemetry.snapshot()
    fresh = snap['bf_win_contribution_freshest_age_seconds{src="3"}']
    stale = snap['bf_win_contribution_stalest_age_seconds{src="3"}']
    assert 0.3 < fresh < 1.0 < stale < 3.0
    hz = telemetry.health()
    assert "3" in hz["contribution_age"]
    assert hz["contribution_age"]["3"]["stalest_sec"] > \
        hz["contribution_age"]["3"]["freshest_sec"]
    # %bfstat renders the line without raising.
    from bluefog_tpu.run.cluster_repl import bfstat_text
    bf.init(lambda: topo.RingGraph(8))
    assert "contribution age" in bfstat_text()


def test_clear_contribution_age_churn_hygiene(trace_env):
    """drop_peer-class hygiene: a dead peer's ranks lose their age
    gauges; survivors' gauges stay."""
    trace_env(BLUEFOG_TPU_TELEMETRY="1")
    telemetry.reset()
    import time
    now_us = time.time_ns() // 1000
    for src in (1, 3, 5):
        W._note_trace_commit("w", src, (src, 1, 0, now_us))
    W.clear_contribution_age([3])
    snap = telemetry.snapshot()
    assert 'bf_win_contribution_freshest_age_seconds{src="3"}' not in snap
    assert 'bf_win_contribution_stalest_age_seconds{src="3"}' not in snap
    assert 'bf_win_contribution_freshest_age_seconds{src="1"}' in snap
    assert 'bf_win_contribution_freshest_age_seconds{src="5"}' in snap
    # None clears everything (transport teardown).
    W.clear_contribution_age()
    snap = telemetry.snapshot()
    assert not any(k.startswith("bf_win_contribution_freshest") or
                   k.startswith("bf_win_contribution_stalest")
                   for k in snap)


def test_telemetry_off_zero_mutation(trace_env):
    trace_env(BLUEFOG_TPU_TELEMETRY="0")
    telemetry.reset()
    import time
    W._note_trace_commit("w", 3, (3, 1, 0, time.time_ns() // 1000))
    assert telemetry.snapshot() == {}
    assert not W._age_minmax


# ---------------------------------------------------------------------------
# Flight recorder: struct pinning, snapshot, dump/load
# ---------------------------------------------------------------------------

def test_rec_event_struct_pinned():
    """The ctypes mirror, the numpy dtype and the C struct must agree —
    a silent layout drift would misparse every dump."""
    assert ctypes.sizeof(native.RecEvent) == 48
    assert flightrec.EVENT_DTYPE.itemsize == 48
    for name, _ in native.RecEvent._fields_:
        assert name in flightrec.EVENT_DTYPE.names


@needs_native
def test_flightrec_snapshot_dump_load(trace_env, tmp_path):
    trace_env(BLUEFOG_TPU_FLIGHT_RECORDER="1")
    assert flightrec.enable()
    flightrec.reset()
    flightrec.note(flightrec.ENQUEUE, op=T.OP_PUT, stripe=2, src=4,
                   dst=1, seq=77, length=1024, name="winname")
    flightrec.note(flightrec.COMMIT, src=4, dst=1, seq=77, name="winname")
    ev = flightrec.snapshot()
    assert len(ev) == 2
    assert int(ev["etype"][0]) == flightrec.ENQUEUE
    assert int(ev["seq"][0]) == 77 and int(ev["stripe"][0]) == 2
    assert ev["name"][0].split(b"\0")[0] == b"winname"
    assert ev["t_us"][1] >= ev["t_us"][0]  # oldest-first
    path = flightrec.dump(path=str(tmp_path / "fr.0.bin"), reason="test")
    header, loaded = flightrec.load(path)
    assert header["unix_us"] > header["mono_us"] >= 0
    np.testing.assert_array_equal(loaded, ev)


@needs_native
def test_flightrec_ring_wraps_oldest_first(trace_env):
    """A ring smaller than the event count keeps the NEWEST events
    (black-box semantics) in order."""
    # The ring is process-global and sized at first enable; emulate wrap
    # by writing far past whatever capacity is live.
    assert flightrec.enable()
    flightrec.reset()
    cap = int(native.lib().bf_rec_enable(0))  # idempotent: live capacity
    n = min(cap + 50, 200_000)
    for i in range(n):
        flightrec.note(flightrec.DRAIN, seq=i + 1)
    ev = flightrec.snapshot()
    assert len(ev) == min(n, cap)
    seqs = ev["seq"].astype(np.int64)
    assert seqs[-1] == n  # newest survived
    assert np.all(np.diff(seqs) == 1)  # contiguous, oldest-first


# ---------------------------------------------------------------------------
# trace-gossip: fake-clock two-rank merge
# ---------------------------------------------------------------------------

def _write_fake_dump(path, rank, unix_us, mono_us, events):
    arr = np.zeros(len(events), flightrec.EVENT_DTYPE)
    for i, e in enumerate(events):
        for k, v in e.items():
            arr[i][k] = v
    with open(path, "wb") as f:
        f.write(flightrec.HEADER.pack(flightrec.MAGIC, flightrec.VERSION,
                                      rank, 0, unix_us, mono_us,
                                      len(arr)))
        f.write(arr.tobytes())


def test_trace_gossip_fake_clock_two_rank_merge(tmp_path):
    """Two synthetic ranks with DIFFERENT clock origins: the merge must
    wall-align them through the anchors and compute the exact one-way
    delay, and the chrome trace must carry the s/f flow pair."""
    prefix = str(tmp_path / "flightrec")
    # Rank 0 (sender): monotonic clock starts at 1_000; anchor says
    # mono 0 == unix 10_000_000.  Its ENQUEUE of tag (src=0, seq=5)
    # happens at mono 1_000 -> wall 10_001_000.
    _write_fake_dump(
        f"{prefix}.0.bin", 0, unix_us=10_000_000, mono_us=0,
        events=[dict(t_us=1_000, src=0, dst=1, seq=5, len=64,
                     etype=flightrec.ENQUEUE, op=T.OP_PUT, name=b"w"),
                dict(t_us=1_200, src=-1, dst=9, seq=1, len=64,
                     etype=flightrec.SENDMSG, op=T.OP_PUT,
                     name=b"h:9")])
    # Rank 1 (receiver): a completely different monotonic origin; anchor
    # mono 500_000 == unix 10_000_000.  Its DECODE of the same tag at
    # mono 501_250 -> wall 10_001_250 => one-way delay 250 us.
    _write_fake_dump(
        f"{prefix}.1.bin", 1, unix_us=10_000_000, mono_us=500_000,
        events=[dict(t_us=501_100, src=0, dst=1, seq=0, len=100,
                     etype=flightrec.DRAIN, op=T.OP_BATCH, name=b""),
                dict(t_us=501_250, src=0, dst=1, seq=5, len=64,
                     etype=flightrec.DECODE,
                     op=T.OP_PUT | T.OP_TRACE_FLAG, name=b"w")])
    dumps = tracegossip.load_dumps(prefix)
    assert [d["rank"] for d in dumps] == [0, 1]
    delays = tracegossip.edge_delays(dumps)
    assert list(delays) == [(0, 1)]
    np.testing.assert_allclose(delays[(0, 1)], [250.0])
    table = tracegossip.delay_table(delays)
    assert "0 -> 1" in table and "0.250" in table

    out, stats = tracegossip.merge_gossip(prefix, dumps=dumps)
    import json
    with open(out) as f:
        merged = json.load(f)
    assert stats["flows_matched"] == 1
    lanes = {e["pid"] for e in merged if e.get("ph") == "X"}
    assert lanes == {0, 1}
    flow_id = (0 << 32) | 5
    s = [e for e in merged if e.get("ph") == "s" and e["id"] == flow_id]
    fin = [e for e in merged if e.get("ph") == "f" and e["id"] == flow_id]
    assert len(s) == 1 and len(fin) == 1
    assert s[0]["pid"] == 0 and fin[0]["pid"] == 1
    # Wall alignment: the arrow spans exactly the 250 us delay.
    assert fin[0]["ts"] - s[0]["ts"] == 250
    # The frame-level SENDMSG event's seq (msgs-in-frame) must NOT have
    # been mistaken for a trace tag.
    assert stats["tags_sent"] == 1


def test_trace_gossip_missing_dumps_raise(tmp_path):
    with pytest.raises(FileNotFoundError):
        tracegossip.load_dumps(str(tmp_path / "nope"))


# ---------------------------------------------------------------------------
# Native commit plumbing: the WinItem trace fields reach the store
# ---------------------------------------------------------------------------

@needs_native
def test_native_commit_entry_carries_trace(trace_env):
    """A tagged native drain item surfaces its tag through
    _commit_native_run into the age telemetry (unit-level: fake entry)."""
    trace_env(BLUEFOG_TPU_TELEMETRY="1")
    telemetry.reset()
    bf.init(lambda: topo.RingGraph(8))
    try:
        assert bf.win_create(np.zeros((8, 4), np.float32), "nc",
                             zero_init=True)
        import time
        now_us = time.time_ns() // 1000
        win = W._store.get("nc")
        (dst, src) = next(iter(win.staging))
        vals = np.arange(4, dtype=np.float32)
        # Mimic _apply_native_items' commit tuple with a live distrib:
        # the store path needs one, so call the commit with the module's
        # single-process distrib shim (None -> parking path would lose
        # the tag; install a minimal stand-in).
        saved = W._store.distrib
        W._store.distrib = W._Distrib(
            object(), rank_owner={r: 0 for r in range(8)},
            proc_addr={0: ("127.0.0.1", 1)}, my_proc=0)
        try:
            W._commit_native_run("nc", [
                ("nc", True, src, dst, 0.0, 1, 0, vals, 16,
                 (src, 9, 0, now_us - 1_000_000))])
        finally:
            W._store.distrib = saved
        np.testing.assert_array_equal(
            np.asarray(win.staging[(dst, src)]), vals)
        pct = telemetry.histogram_percentiles(
            "bf_win_contribution_age_seconds", qs=(50.0,), src=str(src))
        assert pct is not None and 0.5 < pct[50.0] < 2.5
    finally:
        bf.win_free("nc")
