"""Physical-placement tests: interconnect model, routing cost model,
placement optimizer, congestion-aware round packing, and the end-to-end
wiring through ``bf.init``/``set_topology``.

The invariants pinned here mirror the tentpole's acceptance criteria:

  * random-regular(4, n=64) on a simulated 8x8 torus: placement + packing
    cut modeled max-link-load >= 2x vs identity placement, with the
    effective weight matrix bit-identical;
  * shift-structured placements (ring) are never made worse — the
    optimizer always evaluates identity and identity wins ties;
  * the applied permutation only moves ranks to other devices, so real op
    outputs are BIT-identical with placement on or off, and
    ``BLUEFOG_TPU_PLACEMENT=0`` restores enumeration order exactly.
"""

import os

import numpy as np
import pytest

import bluefog_tpu as bf
from bluefog_tpu import basics, topology as topo
from bluefog_tpu.ops import collective as C
from bluefog_tpu.ops import placement as PL
from bluefog_tpu.ops import schedule as S
from bluefog_tpu.ops import schedule_opt as SO
from bluefog_tpu.utils import config, telemetry

N = 8  # virtual mesh size (conftest)

_KNOBS = ("BLUEFOG_TPU_PLACEMENT", "BLUEFOG_TPU_FAKE_TORUS",
          "BLUEFOG_TPU_PLACEMENT_ROUND_BUDGET",
          "BLUEFOG_TPU_PLACEMENT_ITERS", "BLUEFOG_TPU_TORUS_WRAP")


@pytest.fixture(autouse=True)
def _restore_env():
    saved = {k: os.environ.get(k) for k in _KNOBS}
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    config.reload()
    PL.set_active(None, None)


def _env(**kw):
    for k in _KNOBS:
        os.environ.pop(k, None)
    os.environ.update(kw)
    config.reload()


def effective_matrix(sched) -> np.ndarray:
    w = np.diag(np.asarray(sched.self_scale, dtype=float))
    for rnd in sched.rounds:
        for s, d in rnd.pairs:
            assert w[s, d] == 0.0, f"duplicate edge ({s}, {d})"
            w[s, d] = rnd.send_scale[s]
    return w


def assert_valid_rounds(sched):
    for rnd in sched.rounds:
        srcs = [s for s, _ in rnd.pairs]
        dsts = [d for _, d in rnd.pairs]
        assert len(set(srcs)) == len(srcs), "src repeated within a round"
        assert len(set(dsts)) == len(dsts), "dst repeated within a round"
        for s, d in rnd.pairs:
            assert rnd.send_scale[s] != 0.0
            assert rnd.recv_mask[d] == 1.0
            assert rnd.src_of[d] == s


# ---------------------------------------------------------------------------
# Model + routing
# ---------------------------------------------------------------------------

def test_parse_torus_spec():
    assert PL.parse_torus_spec("4x8") == (4, 8)
    assert PL.parse_torus_spec("2x4x4") == (2, 4, 4)
    assert PL.parse_torus_spec("8") == (8,)
    for bad in ("", "0x4", "4x", "axb", "1x1", "2x2x2x2"):
        with pytest.raises(ValueError):
            PL.parse_torus_spec(bad)


def test_route_dimension_ordered_with_wrap():
    m = PL.synthetic_torus((4, 8))
    # Same node: no links.
    assert PL.synthetic_torus((4, 8)).route(0, 0).size == 0
    # One hop along dim 1: exactly one link.
    assert m.route(0, 1).size == 1
    # Wrap beats the long way: node (0,0) -> (0,7) is 1 hop backward,
    # not 7 forward.
    assert m.route(0, 7).size == 1
    # Dimension-ordered total hops = sum of per-dim wrap distances.
    a = 0                      # (0, 0)
    b = 2 * 8 + 3              # (2, 3)
    assert m.route(a, b).size == 2 + 3
    # Deterministic: repeated calls give the identical id sequence.
    assert np.array_equal(m.route(a, b), m.route(a, b))


def test_route_distance_symmetry_and_triangle():
    m = PL.synthetic_torus((4, 8))
    rng = np.random.default_rng(0)
    for _ in range(50):
        a, b = rng.integers(0, 32, size=2)
        assert m.distance(int(a), int(b)) == m.distance(int(b), int(a))
        assert m.route(int(a), int(b)).size == m.distance(int(a), int(b))


def test_cross_slice_routes_use_dcn_link():
    m = PL.TorusModel(name="t", dims=(2, 2), device_node=tuple(range(8)),
                      n_slices=2)
    intra = m.n_nodes * 2 * len(m.dims)
    r = m.route(0, 5)  # slice 0 node -> slice 1 node
    assert r.size == 1 and r[0] >= intra
    assert m.link_weights[r[0]] == m.dcn_link_cost
    # Reverse direction is a DIFFERENT directed DCN link.
    assert m.route(5, 0)[0] != r[0]


def test_build_model_fake_torus_and_fallbacks():
    devs = [object() for _ in range(8)]  # no .coords: flat host
    _env()
    assert PL.build_model(devs) is None
    _env(BLUEFOG_TPU_FAKE_TORUS="2x4")
    m = PL.build_model(devs)
    assert m is not None and m.dims == (2, 4)
    assert m.device_node == tuple(range(8))
    # Size mismatch: warn + disable, never mis-model.
    _env(BLUEFOG_TPU_FAKE_TORUS="4x4")
    assert PL.build_model(devs) is None
    # ... including a divisor count (2x2 is a typo for 2x4, not a request
    # for a devices-share-nodes model).
    _env(BLUEFOG_TPU_FAKE_TORUS="2x2")
    assert PL.build_model(devs) is None
    _env(BLUEFOG_TPU_FAKE_TORUS="garbage")
    assert PL.build_model(devs) is None


def test_build_model_from_device_coords():
    class Dev:
        def __init__(self, coords, slice_index=0):
            self.coords = coords
            self.slice_index = slice_index
    devs = [Dev((x, y, 0)) for x in range(2) for y in range(4)]
    _env()
    m = PL.build_model(devs)
    assert m is not None
    assert m.dims == (2, 4)  # trailing singleton dim dropped
    assert m.n_slices == 1
    two_slice = [Dev((x, y, 0), s) for s in range(2)
                 for x in range(2) for y in range(2)]
    m2 = PL.build_model(two_slice)
    assert m2 is not None and m2.n_slices == 2


def test_mesh_routing_without_wrap():
    # 8-ring with wrap: 0 -> 7 is one backward hop.  As a mesh (sub-pod
    # slice), the only physical path is 7 forward hops.
    torus = PL.TorusModel(name="t", dims=(8,), device_node=tuple(range(8)))
    mesh = PL.TorusModel(name="m", dims=(8,), device_node=tuple(range(8)),
                         wrap=(False,))
    assert torus.route(0, 7).size == 1
    assert mesh.route(0, 7).size == 7
    assert torus.distance(0, 7) == 1.0
    assert mesh.distance(0, 7) == 7.0
    # The direct path is identical where no wrap would be taken.
    assert np.array_equal(torus.route(2, 5), mesh.route(2, 5))


def test_build_model_wrap_policy():
    class Dev:
        def __init__(self, coords, slice_index=0):
            self.coords = coords
            self.slice_index = slice_index
    # 2-D (v2/v3-style) sub-pod slice: auto policy models a mesh.
    flat2d = [Dev((x, y, 0)) for x in range(2) for y in range(4)]
    _env()
    assert PL.build_model(flat2d).wrap_dims == (False, False)
    _env(BLUEFOG_TPU_TORUS_WRAP="1")
    assert PL.build_model(flat2d).wrap_dims == (True, True)
    # 3-D (v4/v5p-style): dims that are multiples of 4 wrap under auto.
    cube = [Dev((x, y, z)) for x in range(4) for y in range(4)
            for z in range(2)]
    _env()
    assert PL.build_model(cube).wrap_dims == (True, True, False)
    _env(BLUEFOG_TPU_TORUS_WRAP="0")
    assert PL.build_model(cube).wrap_dims == (False, False, False)
    # The synthetic fake torus is, by declaration, fully wrapped.
    _env(BLUEFOG_TPU_FAKE_TORUS="2x4", BLUEFOG_TPU_TORUS_WRAP="0")
    m = PL.build_model([object() for _ in range(8)])
    assert m.wrap_dims == (True, True)


# ---------------------------------------------------------------------------
# Cost model
# ---------------------------------------------------------------------------

def test_ring_on_matching_torus_costs_one_hop_per_edge():
    n = 8
    m = PL.synthetic_torus((n,))
    sched = S._build_schedule(topo.weight_matrix(topo.RingGraph(n)),
                              optimize=True)
    c = PL.schedule_cost(m, sched)
    # Bidirectional ring on its own ring: every edge is exactly one hop
    # and no two edges of one round share a link.
    assert c.max_link_load == 1.0
    assert c.hop_bytes == 2 * n  # n edges each way, 1 hop each


def test_schedule_cost_counts_contention():
    # Two edges forced over the same link in one round: load 2.  On a
    # 4-ring, 0->2 and 1->3 both cross the 1->2 link under
    # dimension-ordered routing.
    m = PL.synthetic_torus((4,))
    rounds = [[(0, 2), (1, 3)]]
    ev = PL._Evaluator(m, rounds)
    c = ev.cost(np.arange(4))
    assert c.max_link_load == 2.0  # both routes cross link 1->2
    assert c.hop_bytes == 4.0


def test_vectorized_cost_matches_per_pair_fallback():
    # The annealer's hot path gathers from the dense route table; models
    # too large for it fall back to per-pair routing.  Same numbers.
    m = PL.synthetic_torus((4, 8))
    n = 32
    sched = S._build_schedule(
        topo.weight_matrix(topo.RandomRegularGraph(n, 4, seed=2)),
        optimize=True)
    rounds = PL.schedule_rounds(sched)
    fast = PL._Evaluator(m, rounds)
    assert fast._tab is not None
    slow = PL._Evaluator(m, rounds)
    slow._tab = None
    rng = np.random.default_rng(0)
    for _ in range(3):
        perm = rng.permutation(n)
        a, b = fast.cost(perm), slow.cost(perm)
        assert a.max_link_load == b.max_link_load
        assert a.hop_bytes == b.hop_bytes
        assert a.serial_link_time == b.serial_link_time


# ---------------------------------------------------------------------------
# Placement optimizer
# ---------------------------------------------------------------------------

def test_placement_deterministic():
    m = PL.synthetic_torus((4, 8))
    sched = S._build_schedule(
        topo.weight_matrix(topo.RandomRegularGraph(32, 4, seed=0)),
        optimize=True)
    r1 = PL.optimize_placement(m, sched, 32, iters=200, seed=3)
    r2 = PL.optimize_placement(m, sched, 32, iters=200, seed=3)
    assert np.array_equal(r1.perm, r2.perm)


def test_shift_structured_never_made_worse():
    m = PL.synthetic_torus((4, 8))
    for make in (lambda: topo.RingGraph(32),
                 lambda: topo.ExponentialTwoGraph(32)):
        sched = S._build_schedule(topo.weight_matrix(make()), optimize=True)
        res = PL.optimize_placement(m, sched, 32, iters=200, seed=0)
        assert (res.optimized_cost.max_link_load
                <= res.identity_cost.max_link_load)
        assert res.improvement_ratio >= 1.0
    # The ring in enumeration order is already optimal: identity wins.
    ring = S._build_schedule(topo.weight_matrix(topo.RingGraph(32)),
                             optimize=True)
    res = PL.optimize_placement(m, ring, 32, iters=200, seed=0)
    assert res.is_identity


def test_acceptance_random_regular_8x8_cut_2x():
    """The tentpole acceptance bar: rr(4, n=64) on a simulated 8x8 torus,
    placement + congestion packing cut modeled max-link-load >= 2x vs
    identity placement, bit-identical effective weight matrix."""
    m = PL.synthetic_torus((8, 8))
    sched = S._build_schedule(
        topo.weight_matrix(topo.RandomRegularGraph(64, 4, seed=0)),
        optimize=True)
    res = PL.optimize_placement(m, sched, 64, iters=1000, seed=0)
    packed = SO.congestion_aware_repack(sched, m, res.perm,
                                        budget_factor=2.0)
    pc = PL.schedule_cost(m, packed, res.perm)
    assert res.identity_cost.max_link_load / pc.max_link_load >= 2.0
    assert np.array_equal(effective_matrix(sched), effective_matrix(packed))
    assert_valid_rounds(packed)


def test_placement_block_constraint_keeps_machine_locality():
    """Multi-process runs constrain the search to permute within
    enumeration-order machine blocks: the hierarchical (machine, local)
    mesh reshapes consecutive device blocks, so a cross-machine swap
    would silently route LOCAL_AXIS collectives over DCN."""
    m = PL.synthetic_torus((4, 8))
    sched = S._build_schedule(
        topo.weight_matrix(topo.RandomRegularGraph(32, 4, seed=0)),
        optimize=True)
    res = PL.optimize_placement(m, sched, 32, iters=300, seed=0, block=8)
    ranks = np.arange(32)
    assert np.array_equal(res.perm // 8, ranks // 8)
    assert (res.optimized_cost.max_link_load
            <= res.identity_cost.max_link_load)
    # A block that does not divide n disables the search: identity only.
    assert PL.optimize_placement(m, sched, 32, iters=50, seed=0,
                                 block=5).is_identity
    # Singleton blocks admit only the identity permutation.
    assert PL.optimize_placement(m, sched, 32, iters=50, seed=0,
                                 block=1).is_identity


def test_joint_optimization_over_dynamic_phases():
    m = PL.synthetic_torus((4, 8))
    g = topo.ExponentialTwoGraph(32)
    static = S.compile_static(g)
    dyn = S.compile_dynamic(topo.dynamic_phase_table(g), 32)
    res = PL.optimize_placement(m, [static, dyn], 32, iters=200, seed=0)
    # Joint cost covers every phase: the report's round count is the union.
    assert res.optimized_cost.rounds >= len(static.rounds) + dyn.period
    assert (res.optimized_cost.max_link_load
            <= res.identity_cost.max_link_load)


# ---------------------------------------------------------------------------
# Congestion-aware repack
# ---------------------------------------------------------------------------

def test_congestion_repack_preserves_semantics():
    m = PL.synthetic_torus((4, 8))
    w = topo.weight_matrix(topo.RandomRegularGraph(32, 4, seed=2))
    sched = S._build_schedule(w, optimize=True)
    packed = SO.congestion_aware_repack(sched, m, None, budget_factor=2.0)
    assert_valid_rounds(packed)
    assert np.array_equal(effective_matrix(sched), effective_matrix(packed))
    # Budget: never beyond 2x the König bound.
    assert len(packed.rounds) <= 2 * SO.min_rounds(sched)
    # Never worse on the primary objective.
    assert (PL.schedule_cost(m, packed).max_link_load
            <= PL.schedule_cost(m, sched).max_link_load)


def test_congestion_repack_disabled_and_noop_paths():
    m = PL.synthetic_torus((4, 8))
    sched = S._build_schedule(
        topo.weight_matrix(topo.RandomRegularGraph(32, 4, seed=0)),
        optimize=True)
    assert SO.congestion_aware_repack(sched, m, None,
                                      budget_factor=0.0) is sched
    assert SO.congestion_aware_repack(sched, None, None) is sched
    # A ring already at load 1 has nothing to split: identical object.
    ring = S._build_schedule(topo.weight_matrix(topo.RingGraph(32)),
                             optimize=True)
    assert SO.congestion_aware_repack(ring, m, None) is ring
    # Mismatched rank count (e.g. machine-level schedule): untouched.
    small = S._build_schedule(topo.weight_matrix(topo.RingGraph(4)),
                              optimize=True)
    assert SO.congestion_aware_repack(small, m, None) is small


# ---------------------------------------------------------------------------
# Wire stats + slot-table caching
# ---------------------------------------------------------------------------

def test_wire_stats_hops_third_element():
    g = topo.ExponentialTwoGraph(8)
    sched = S.compile_static(g)
    assert C.schedule_wire_stats(sched)[2] is None
    m = PL.synthetic_torus((2, 4))
    perm = np.arange(8)
    PL.set_active(m, perm)
    try:
        r, e, hops, _prov = C.schedule_wire_stats(sched)
        assert hops is not None and hops > 0
        assert hops == PL.schedule_cost(m, sched, perm).hop_bytes
        # Cached per schedule object: second call returns the same value.
        assert C.schedule_wire_stats(sched)[2] == hops
        # Dynamic: per-call average over phases.
        dyn = S.compile_dynamic(topo.one_peer_exp2_phases(8), 8)
        dr, de, dhops, _dprov = C.schedule_wire_stats(dyn)
        per = [PL.schedule_cost(m, ph, perm).hop_bytes for ph in dyn.phases]
        assert dhops == pytest.approx(sum(per) / len(per))
        # Mismatched rank count: no hops (machine-level schedules).
        small = S.compile_static(topo.RingGraph(4))
        assert C.schedule_wire_stats(small)[2] is None
    finally:
        PL.set_active(None, None)
    assert C.schedule_wire_stats(sched)[2] is None


def test_modeled_hops_survives_non_weakrefable_schedule():
    # Schedule stand-ins without weakref support (e.g. __slots__ types)
    # must degrade to "no hops", not TypeError out of the cache probe.
    class SlotsSched:
        __slots__ = ("n",)

        def __init__(self, n):
            self.n = n
    m = PL.synthetic_torus((2, 4))
    PL.set_active(m, np.arange(8))
    try:
        assert PL.modeled_schedule_hops(SlotsSched(4)) is None  # n mismatch
        sched = S.compile_static(topo.RingGraph(8))
        assert PL.modeled_schedule_hops(sched) > 0
    finally:
        PL.set_active(None, None)


def test_slot_tables_cached_on_schedule():
    sched = S.compile_static(topo.StarGraph(8))
    t1 = sched.slot_tables
    assert t1 is sched.slot_tables  # cached, not rebuilt per access
    # The legacy helper delegates to the cache and agrees with the oracle.
    legacy = C._slot_tables(sched)
    assert len(legacy) == len(sched.rounds)
    for a, b in zip(legacy, t1):
        assert np.array_equal(a, b)
    in_nbrs = [[] for _ in range(8)]
    for rnd in sched.rounds:
        for s, d in rnd.pairs:
            in_nbrs[d].append(s)
    for lst in in_nbrs:
        lst.sort()
    for rnd, slots in zip(sched.rounds, t1):
        for dst in range(8):
            s = rnd.src_of[dst]
            if s >= 0:
                assert slots[dst] == in_nbrs[dst].index(int(s))
            else:
                assert slots[dst] == -1


# ---------------------------------------------------------------------------
# End-to-end wiring through bf.init / set_topology
# ---------------------------------------------------------------------------

def _run_op(topo_fn, x):
    bf.init(topo_fn)
    out = np.asarray(bf.neighbor_allreduce(x))
    info = bf.placement_info()
    devices = list(basics._ctx.devices)
    bf.shutdown()
    return out, info, devices


def test_end_to_end_bit_identical_and_env_hatch(devices):
    topo_fn = lambda: topo.RandomRegularGraph(N, 4, seed=1)
    x = np.random.default_rng(0).standard_normal((N, 16)).astype(np.float32)

    _env(BLUEFOG_TPU_PLACEMENT="0", BLUEFOG_TPU_FAKE_TORUS="2x4")
    out_off, info_off, devs_off = _run_op(topo_fn, x)
    assert info_off is None  # PLACEMENT=0: no model, no permutation
    assert devs_off == devices[:N]  # enumeration order exactly

    _env(BLUEFOG_TPU_PLACEMENT="1", BLUEFOG_TPU_FAKE_TORUS="2x4",
         BLUEFOG_TPU_PLACEMENT_ROUND_BUDGET="0")
    out_place, info_on, devs_on = _run_op(topo_fn, x)
    assert info_on is not None
    assert info_on["max_link_load_opt"] <= info_on["max_link_load_naive"]
    # The permutation is a permutation OF the same devices...
    assert sorted(map(str, devs_on)) == sorted(map(str, devs_off))
    # ...and outputs are BIT-identical: only the physical chip moved.
    assert np.array_equal(out_off, out_place)

    # Congestion packing on: fp summation order may shift, never more.
    _env(BLUEFOG_TPU_PLACEMENT="1", BLUEFOG_TPU_FAKE_TORUS="2x4")
    out_pack, _, _ = _run_op(topo_fn, x)
    assert float(np.abs(out_off - out_pack).max()) <= 1e-6


def test_dispatch_records_hop_bytes(devices):
    _env(BLUEFOG_TPU_PLACEMENT="1", BLUEFOG_TPU_FAKE_TORUS="2x4")
    telemetry.reset()
    bf.init(lambda: topo.ExponentialTwoGraph(N))
    x = np.arange(N * 4, dtype=np.float32).reshape(N, 4)
    bf.neighbor_allreduce(x)
    snap = telemetry.snapshot()
    key = 'bf_schedule_hop_bytes_total{op="neighbor_allreduce"}'
    assert snap.get(key, 0) > 0
    assert snap.get("bf_placement_improvement_ratio", 0) >= 1.0
    assert "bf_schedule_max_link_load" in snap
    bf.shutdown()


def test_placement_gauges_cleared_when_model_inactive(devices):
    """Deactivating the model (PLACEMENT=0, flat host, ...) must clear the
    placement gauges — a stale last value would misreport /metrics."""
    _env(BLUEFOG_TPU_PLACEMENT="1", BLUEFOG_TPU_FAKE_TORUS="2x4")
    telemetry.reset()
    bf.init(lambda: topo.RandomRegularGraph(N, 4, seed=0))
    assert "bf_placement_improvement_ratio" in telemetry.snapshot()
    bf.shutdown()
    _env(BLUEFOG_TPU_PLACEMENT="0", BLUEFOG_TPU_FAKE_TORUS="2x4")
    bf.init(lambda: topo.RandomRegularGraph(N, 4, seed=0))
    snap = telemetry.snapshot()
    assert "bf_placement_improvement_ratio" not in snap
    assert "bf_schedule_max_link_load" not in snap
    bf.shutdown()


def test_max_link_load_gauge_priced_on_packed_schedule(devices):
    """The gauge describes what dispatches: the placed AND congestion-
    packed schedules (docs/observability.md), never more than the
    pre-pack placement cost — and the pricing repack must not bump the
    moves counter (record=False), which only counts dispatched repacks."""
    _env(BLUEFOG_TPU_PLACEMENT="1", BLUEFOG_TPU_FAKE_TORUS="2x4")
    telemetry.reset()
    bf.init(lambda: topo.RandomRegularGraph(N, 4, seed=1))
    snap = telemetry.snapshot()
    info = bf.placement_info()
    gauge = snap.get("bf_schedule_max_link_load")
    assert gauge is not None and gauge > 0
    assert gauge <= info["max_link_load_opt"]
    assert not snap.get("bf_schedule_congestion_moves_total")
    bf.shutdown()


def test_placement_search_memoized_across_set_topology(devices):
    """Re-installing a previously seen topology must not redo the search:
    the result is memoized on schedule structure (the search is a
    multi-second affair on big meshes)."""
    _env(BLUEFOG_TPU_PLACEMENT="1", BLUEFOG_TPU_FAKE_TORUS="2x4")
    bf.init(lambda: topo.RandomRegularGraph(N, 4, seed=1))
    first = basics._ctx.placement_result
    assert first is not None
    bf.set_topology(topo.RingGraph(N))
    bf.set_topology(topo.RandomRegularGraph(N, 4, seed=1))
    assert basics._ctx.placement_result is first  # memo hit, same object
    # One interconnect model serves every set_topology (route caches are
    # the expensive part and devices never change within a process).
    assert len(basics._placement_model_cache) == 1
    bf.shutdown()


def test_placement_generation_keys_schedule_cache(devices):
    """Schedule cache keys carry the placement generation: a schedule a
    racing dispatch compiled (and congestion-repacked) against the
    OUTGOING placement mid-set_topology is keyed to the old generation and
    never served after the refresh publishes the new one."""
    _env(BLUEFOG_TPU_PLACEMENT="1", BLUEFOG_TPU_FAKE_TORUS="2x4")
    bf.init(lambda: topo.RandomRegularGraph(N, 4, seed=1))
    ctx = basics._ctx
    g0 = ctx.placement_generation
    bf.neighbor_allreduce(np.ones((N, 4), np.float32))
    assert all(k[-1] == g0 for k in ctx._static_scheds)
    bf.set_topology(topo.RingGraph(N))
    g1 = ctx.placement_generation
    assert g1 > g0
    bf.neighbor_allreduce(np.ones((N, 4), np.float32))
    assert ctx._static_scheds and all(
        k[-1] == g1 for k in ctx._static_scheds)
    # _physical_repack reads (model, perm) as one snapshot.
    model, perm = ctx._placement_state
    assert model is ctx.placement_model
    assert perm is ctx.placement
    bf.shutdown()


def test_slow_path_search_iters_capped():
    """Above the dense-route-table cutoff the annealer routes per edge in
    Python; the iteration cap must bound the default-on search so a
    pod-scale init() never blocks for minutes."""
    import time
    n = 18 * 16  # 288 > _VECTOR_TABLE_MAX_NODES=256
    model = PL.synthetic_torus((18, 16))
    assert model.route_table is None
    sched = S.compile_static(topo.RandomRegularGraph(n, 4, seed=1))
    t = time.time()
    res = PL.optimize_placement(model, [sched], n, iters=10_000, seed=0)
    took = time.time() - t
    assert took < 60, f"guarded slow-path search took {took:.0f}s"
    assert res.optimized_cost.max_link_load <= \
        res.identity_cost.max_link_load


def test_placement_gives_consensus_identical_mean(devices):
    """Gossip under a permuted mesh still preserves the global mean (the
    weight matrix is untouched, so column-stochasticity is too)."""
    _env(BLUEFOG_TPU_PLACEMENT="1", BLUEFOG_TPU_FAKE_TORUS="2x4")
    bf.init(lambda: topo.RandomRegularGraph(N, 4, seed=0))
    x = np.random.default_rng(1).standard_normal((N, 8)).astype(np.float32)
    out = np.asarray(bf.neighbor_allreduce(x))
    np.testing.assert_allclose(out.mean(axis=0), x.mean(axis=0),
                               rtol=1e-5, atol=1e-6)
    bf.shutdown()
