"""Torch interop layer tests (mirrors the reference's second-frontend tests,
``test/tensorflow_ops_test.py``)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import bluefog_tpu as bf  # noqa: E402
import bluefog_tpu.torch as bft  # noqa: E402
from bluefog_tpu import topology as topo  # noqa: E402

N = 8


def setup_function(_fn):
    bf.init(lambda: topo.ExponentialTwoGraph(N))


def test_torch_allreduce_and_broadcast():
    x = torch.arange(N, dtype=torch.float32).reshape(N, 1) + 1
    out = bft.allreduce(x, average=True)
    assert torch.allclose(out, torch.full((N, 1), 4.5))
    b = bft.broadcast(x, root_rank=2)
    assert torch.allclose(b, torch.full((N, 1), 3.0))


def test_torch_allgather_dtype_preserved():
    x = torch.ones(N, 2, dtype=torch.float64)
    out = bft.allgather(x)
    assert out.dtype == torch.float64
    assert out.shape == (N, N * 2)


def test_torch_neighbor_allreduce_consensus():
    x = torch.randn(N, 16)
    target = x.mean(0)
    y = x.clone()
    for _ in range(60):
        y = bft.neighbor_allreduce(y)
    assert torch.allclose(y, target.expand_as(y), atol=1e-4)


def test_torch_module_replicas_consensus():
    models = [torch.nn.Linear(4, 2) for _ in range(N)]
    bft.neighbor_allreduce_module_(models)
    for _ in range(40):
        bft.neighbor_allreduce_module_(models)
    w0 = models[0].weight.detach()
    for m in models[1:]:
        assert torch.allclose(m.weight.detach(), w0, atol=1e-5)


def test_torch_replicate_and_broadcast_parameters():
    m = torch.nn.Linear(3, 3)
    stacked = bft.replicate_module(m)
    assert all(v.shape[0] == N for v in stacked.values())
    # perturb non-root replicas, then broadcast root 0 back out
    for k in stacked:
        stacked[k][1:] += 1.0
    synced = bft.broadcast_parameters(stacked, root_rank=0)
    for k, v in synced.items():
        for r in range(N):
            assert torch.allclose(v[r], stacked[k][0])
    m2 = torch.nn.Linear(3, 3)
    bft.load_replica(m2, synced, rank=3)
    assert torch.allclose(m2.weight, m.weight)


def _make_regression_world(seed=0):
    """Per-rank linear regression data with distinct rank-local optima; the
    global least-squares solution is only reachable through communication."""
    g = torch.Generator().manual_seed(seed)
    w_true = torch.tensor([[2.0], [-1.0]])
    Xs, ys = [], []
    for r in range(N):
        X = torch.randn(32, 2, generator=g) + 0.5 * r  # rank-skewed inputs
        ys.append(X @ w_true + 0.05 * torch.randn(32, 1, generator=g))
        Xs.append(X)
    replicas = []
    for r in range(N):
        torch.manual_seed(100 + r)  # deliberately diverged starts
        replicas.append(torch.nn.Linear(2, 1, bias=False))
    return Xs, ys, replicas, w_true


def _global_lstsq(Xs, ys):
    X = torch.cat(Xs)
    y = torch.cat(ys)
    return torch.linalg.lstsq(X, y).solution


@pytest.mark.parametrize("mode", ["gradient_allreduce", "neighbor_allreduce",
                                  "allreduce"])
@pytest.mark.slow
def test_torch_distributed_optimizer_end_to_end(mode):
    """Full decentralized training loop through the torch frontend: module
    replicas + per-rank optimizers + the DistributedOptimizer wrapper reach
    the *global* least-squares solution and inter-replica consensus
    (scope match: reference tensorflow/optimizers.py:135-203)."""
    Xs, ys, replicas, _ = _make_regression_world()
    w_star = _global_lstsq(Xs, ys)
    if mode == "gradient_allreduce":
        bft.broadcast_module_(replicas)  # DP-1 requires identical starts
    opt = bft.DistributedOptimizer(
        replicas, lambda ps: torch.optim.Adam(ps, lr=0.05),
        communication_type=mode)
    loss_fn = torch.nn.MSELoss()
    for _ in range(600):
        opt.zero_grad()
        loss = sum(loss_fn(m(Xs[r]), ys[r])
                   for r, m in enumerate(replicas)) / N
        loss.backward()
        opt.step()
    weights = torch.stack([m.weight.detach().reshape(-1)
                           for m in replicas])
    # consensus: replicas agree
    spread = float((weights - weights.mean(0)).abs().max())
    assert spread < 5e-2, f"{mode}: replicas disagree by {spread}"
    # optimality: agreement point is the global solution, not a local one
    err = float((weights.mean(0) - w_star.reshape(-1)).abs().max())
    assert err < 5e-2, f"{mode}: {err} from global lstsq solution"


def test_torch_distributed_optimizer_empty_mode_diverges():
    """Sanity check on the harness itself: with communication off, the
    rank-skewed data keeps replicas apart — proving the convergence above
    comes from the communication, not the shared loss."""
    Xs, ys, replicas, _ = _make_regression_world()
    opt = bft.DistributedOptimizer(
        replicas, lambda ps: torch.optim.SGD(ps, lr=0.02),
        communication_type="empty")
    loss_fn = torch.nn.MSELoss()
    for _ in range(300):
        opt.zero_grad()
        loss = sum(loss_fn(m(Xs[r]), ys[r])
                   for r, m in enumerate(replicas)) / N
        loss.backward()
        opt.step()
    weights = torch.stack([m.weight.detach().reshape(-1)
                           for m in replicas])
    spread = float((weights - weights.mean(0)).abs().max())
    assert spread > 5e-2, f"expected divergence without comm, spread={spread}"


def test_torch_distributed_optimizer_validates_args():
    _, _, replicas, _ = _make_regression_world()
    with pytest.raises(ValueError, match="communication_type"):
        bft.DistributedOptimizer(replicas, lambda ps:
                                 torch.optim.SGD(ps, lr=0.1),
                                 communication_type="bogus")
    with pytest.raises(AssertionError):
        bft.DistributedOptimizer(replicas[:2], lambda ps:
                                 torch.optim.SGD(ps, lr=0.1))


def test_torch_distributed_optimizer_buffer_consensus():
    """Consensus modes must cover floating-point buffers too: BatchNorm
    running stats reach agreement, so any single replica checkpoints as
    'the' model; the integer step counter is left alone."""
    replicas = []
    for r in range(N):
        torch.manual_seed(r)
        replicas.append(torch.nn.Sequential(torch.nn.Linear(4, 4),
                                            torch.nn.BatchNorm1d(4)))
    opt = bft.DistributedOptimizer(
        replicas, lambda ps: torch.optim.SGD(ps, lr=0.01),
        communication_type="allreduce")
    for step in range(3):
        opt.zero_grad()
        loss = sum(m(torch.randn(8, 4) + r).square().mean()
                   for r, m in enumerate(replicas))
        loss.backward()
        opt.step()
    means = torch.stack([replicas[r][1].running_mean for r in range(N)])
    assert float((means - means.mean(0)).abs().max()) < 1e-6
    counts = [int(replicas[r][1].num_batches_tracked) for r in range(N)]
    assert counts == [3] * N  # integer buffers never averaged


def test_torch_gradient_allreduce_handles_none_grads():
    """A rank whose parameter got no gradient contributes zero to the DP-1
    average instead of silently desynchronizing the replicas."""
    replicas = [torch.nn.Linear(2, 1, bias=False) for _ in range(N)]
    bft.broadcast_module_(replicas)
    opt = bft.DistributedOptimizer(
        replicas, lambda ps: torch.optim.SGD(ps, lr=0.5),
        communication_type="gradient_allreduce")
    opt.zero_grad()
    # only even ranks produce gradients this step
    loss = sum(replicas[r](torch.ones(1, 2)).sum()
               for r in range(0, N, 2))
    loss.backward()
    opt.step()
    weights = torch.stack([m.weight.detach() for m in replicas])
    spread = float((weights - weights.mean(0)).abs().max())
    assert spread < 1e-7, f"replicas desynchronized: {spread}"


def test_torch_allreduce_gradient_flows():
    """Gradient of an (average) allreduce is the averaged upstream gradient
    (reference TF gradient registration, tensorflow/mpi_ops.py:95-105)."""
    bf.init()
    n = bf.size()
    x = torch.randn(n, 3, requires_grad=True)
    out = bft.allreduce(x)
    c = torch.randn(n, 3)
    (out * c).sum().backward()
    expected = np.broadcast_to(np.asarray(c).mean(0), (n, 3))
    np.testing.assert_allclose(x.grad.numpy(), expected, rtol=1e-5,
                               atol=1e-6)
    # sum flavor: every row collects the column sum
    x2 = torch.randn(n, 3, requires_grad=True)
    (bft.allreduce(x2, average=False) * c).sum().backward()
    np.testing.assert_allclose(
        x2.grad.numpy(), np.broadcast_to(np.asarray(c).sum(0), (n, 3)),
        rtol=1e-5, atol=1e-5)


def test_torch_neighbor_allreduce_gradient_is_transposed_combine():
    """out = W^T x  =>  dL/dx = W g: the backward runs the combine along
    reversed edges.  Checked against the dense matrix product on a
    DIRECTED ring (W != W^T, so a wrong transpose direction fails)."""
    bf.init(lambda: topo.RingGraph(8, connect_style=1))
    n = 8
    from bluefog_tpu.ops import schedule as S
    W = S.uniform_weights(topo.weight_matrix(bf.load_topology()))
    x = torch.randn(n, 4, requires_grad=True, dtype=torch.float64)
    out = bft.neighbor_allreduce(x)
    np.testing.assert_allclose(
        out.detach().numpy(), W.T @ x.detach().numpy(), rtol=1e-5)
    g = torch.randn(n, 4, dtype=torch.float64)
    (out * g).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), W @ g.numpy(), rtol=1e-5)


def test_torch_broadcast_gradient_concentrates_on_root():
    bf.init()
    n = bf.size()
    x = torch.randn(n, 2, requires_grad=True)
    g = torch.randn(n, 2)
    (bft.broadcast(x, 3) * g).sum().backward()
    expected = np.zeros((n, 2), np.float32)
    expected[3] = np.asarray(g).sum(0)
    np.testing.assert_allclose(x.grad.numpy(), expected, rtol=1e-5,
                               atol=1e-6)


def test_torch_allgather_gradient_scatters_segments():
    bf.init()
    n = bf.size()
    x = torch.randn(n, 2, 3, requires_grad=True)
    out = bft.allgather(x)          # (n, n*2, 3)
    g = torch.randn(*out.shape)
    (out * g).sum().backward()
    expected = np.asarray(g).reshape(n, n, 2, 3).sum(0)
    np.testing.assert_allclose(x.grad.numpy(), expected, rtol=1e-5,
                               atol=1e-5)


def test_torch_training_through_communication():
    """A torch model trains THROUGH a differentiable neighbor_allreduce in
    its loss graph — the capability the reference's TF gradient
    registration exists for."""
    bf.init(lambda: topo.ExponentialGraph(8))
    n = 8
    torch.manual_seed(0)
    w = torch.randn(n, 4, 1, requires_grad=True)
    A = torch.randn(n, 16, 4)
    target = torch.randn(4, 1)
    y = A @ target
    opt = torch.optim.SGD([w], lr=0.1)
    for _ in range(600):
        opt.zero_grad()
        # combine-then-predict: gradients must flow back through the
        # neighbor combine to EVERY contributing rank's weights
        combined = bft.neighbor_allreduce(w)
        loss = ((A @ combined - y) ** 2).mean()
        loss.backward()
        assert w.grad is not None and float(w.grad.abs().sum()) > 0
        opt.step()
    final = ((A @ bft.neighbor_allreduce(w) - y) ** 2).mean()
    assert float(final) < 0.05, float(final)


_MP_TORCH_SCRIPT = r"""
import sys
sys.path.insert(0, "@REPO@")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import torch
import bluefog_tpu as bf
import bluefog_tpu.torch as bft

bf.init_distributed()
n = bf.size()

# Differentiable collective over the REAL multi-process path: the result is
# a coordinator-gathered rank-major torch tensor on every process, and
# gradients flow through the transposed combine.
x = torch.arange(n, dtype=torch.float32).reshape(n, 1).requires_grad_(True)
y = bft.allreduce(x, average=True)
assert y.shape == (n, 1)
np.testing.assert_allclose(y.detach().numpy(),
                           np.full((n, 1), (n - 1) / 2.0), rtol=1e-6)
y.sum().backward()
np.testing.assert_allclose(x.grad.numpy(), np.ones((n, 1)), rtol=1e-6)

# Neighbor averaging through the frontend, same mp transport.
z = torch.eye(n)
out = bft.neighbor_allreduce(z)
w = out.numpy()
np.testing.assert_allclose(w.sum(axis=1), np.ones(n), rtol=1e-5)
print("MP-TORCH-OK", jax.process_index(), flush=True)
"""


@pytest.mark.slow
def test_torch_bridge_under_bfrun(tmp_path):
    """The torch frontend (second-framework role) under a REAL bfrun
    multi-process launch: collectives gather non-addressable shards into
    rank-major host tensors and stay differentiable."""
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "prog.py"
    script.write_text(_MP_TORCH_SCRIPT.replace("@REPO@", repo))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.run", "-np", "2",
         "--devices-per-proc", "2", sys.executable, str(script)],
        capture_output=True, text=True, timeout=600, cwd=repo, env=env)
    assert out.returncode == 0, \
        f"stdout={out.stdout}\nstderr={out.stderr[-4000:]}"
    assert out.stdout.count("MP-TORCH-OK") == 2, out.stdout
