"""Torch interop layer tests (mirrors the reference's second-frontend tests,
``test/tensorflow_ops_test.py``)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import bluefog_tpu as bf  # noqa: E402
import bluefog_tpu.torch as bft  # noqa: E402
from bluefog_tpu import topology as topo  # noqa: E402

N = 8


def setup_function(_fn):
    bf.init(lambda: topo.ExponentialTwoGraph(N))


def test_torch_allreduce_and_broadcast():
    x = torch.arange(N, dtype=torch.float32).reshape(N, 1) + 1
    out = bft.allreduce(x, average=True)
    assert torch.allclose(out, torch.full((N, 1), 4.5))
    b = bft.broadcast(x, root_rank=2)
    assert torch.allclose(b, torch.full((N, 1), 3.0))


def test_torch_allgather_dtype_preserved():
    x = torch.ones(N, 2, dtype=torch.float64)
    out = bft.allgather(x)
    assert out.dtype == torch.float64
    assert out.shape == (N, N * 2)


def test_torch_neighbor_allreduce_consensus():
    x = torch.randn(N, 16)
    target = x.mean(0)
    y = x.clone()
    for _ in range(60):
        y = bft.neighbor_allreduce(y)
    assert torch.allclose(y, target.expand_as(y), atol=1e-4)


def test_torch_module_replicas_consensus():
    models = [torch.nn.Linear(4, 2) for _ in range(N)]
    bft.neighbor_allreduce_module_(models)
    for _ in range(40):
        bft.neighbor_allreduce_module_(models)
    w0 = models[0].weight.detach()
    for m in models[1:]:
        assert torch.allclose(m.weight.detach(), w0, atol=1e-5)


def test_torch_replicate_and_broadcast_parameters():
    m = torch.nn.Linear(3, 3)
    stacked = bft.replicate_module(m)
    assert all(v.shape[0] == N for v in stacked.values())
    # perturb non-root replicas, then broadcast root 0 back out
    for k in stacked:
        stacked[k][1:] += 1.0
    synced = bft.broadcast_parameters(stacked, root_rank=0)
    for k, v in synced.items():
        for r in range(N):
            assert torch.allclose(v[r], stacked[k][0])
    m2 = torch.nn.Linear(3, 3)
    bft.load_replica(m2, synced, rank=3)
    assert torch.allclose(m2.weight, m.weight)
