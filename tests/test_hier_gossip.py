"""Hierarchical two-level gossip (BLUEFOG_TPU_HIER): the
``topology.HierarchicalTopology`` artifact, the ``collective.
hierarchical_gossip`` executor (dense ICI inner x sparse DCN outer,
cadence, per-level compression), the ``sparse:<frac>`` window wire codec
with sender-side error feedback, the per-level telemetry, and the
satellite coverage — legacy inner/outer generator structure, the churn
supervisor driven from window-optimizer ``step()``, and the dynamically
enumerated compression vocabulary.
"""

import os

import numpy as np
import pytest

import bluefog_tpu as bf
from bluefog_tpu import topology as topo
from bluefog_tpu.utils import config

N = 8  # virtual mesh size (conftest)

_KNOBS = ("BLUEFOG_TPU_HIER", "BLUEFOG_TPU_HIER_OUTER_EVERY",
          "BLUEFOG_TPU_HIER_INNER", "BLUEFOG_TPU_HIER_OUTER",
          "BLUEFOG_TPU_HIER_OUTER_COMPRESSION",
          "BLUEFOG_TPU_HIER_OUTER_SELF_WEIGHT",
          "BLUEFOG_TPU_WIN_COMPRESSION", "BLUEFOG_TPU_FAKE_TORUS",
          "BLUEFOG_TPU_PLACEMENT", "BLUEFOG_TPU_CHURN")


@pytest.fixture(autouse=True)
def _restore_env():
    saved = {k: os.environ.get(k) for k in _KNOBS}
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    config.reload()


def _env(**kw):
    for k in _KNOBS:
        os.environ.pop(k, None)
    os.environ.update(kw)
    config.reload()


def _rank_major(seed=0, shape=(N, 6)):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


# ---------------------------------------------------------------------------
# HierarchicalTopology artifact
# ---------------------------------------------------------------------------

def _assert_doubly_stochastic(w):
    np.testing.assert_allclose(w.sum(axis=0), 1.0, atol=1e-12)
    np.testing.assert_allclose(w.sum(axis=1), 1.0, atol=1e-12)


@pytest.mark.parametrize("n,slices,k,theta", [
    (8, 2, 1, 0.5), (8, 2, 2, 0.5), (16, 4, 3, 0.7), (12, 3, 2, 0.4)])
def test_effective_matrices_doubly_stochastic(n, slices, k, theta):
    ht = topo.hierarchical_two_level(n, slices, outer_every=k,
                                     outer_self_weight=theta)
    for step in range(ht.period * 2):
        _assert_doubly_stochastic(ht.effective_weight_matrix(step))


def test_cadence_corrected_self_weight():
    theta = 0.5
    for k in (1, 2, 3):
        ht = topo.hierarchical_two_level(8, 2, outer_every=k,
                                         outer_self_weight=theta)
        assert ht.outer_self_weight == pytest.approx(theta ** k)
    raw = topo.hierarchical_two_level(8, 2, outer_every=3,
                                      outer_self_weight=theta,
                                      cadence_corrected=False)
    assert raw.outer_self_weight == theta


def test_cadence_and_phase_policy():
    ht = topo.hierarchical_two_level(16, 4, outer_every=2)
    assert len(ht.outer_phases) == 2  # exp2 over 4 slices: shifts 1, 2
    assert ht.period == 4
    assert [ht.is_outer_step(s) for s in range(4)] == [
        True, False, True, False]
    # Default: phase advances once per outer step.
    assert [ht.outer_phase_index(s) for s in (0, 2, 4, 6)] == [0, 1, 0, 1]
    # Sparse sweep-hold: the phase is pinned for sweep_len outer steps.
    assert [ht.outer_phase_index(s, sweep_len=2)
            for s in (0, 2, 4, 6)] == [0, 0, 1, 1]


def test_inner_only_steps_have_no_dcn_edges():
    ht = topo.hierarchical_two_level(8, 2, outer_every=3)
    slice_of = np.arange(8) // 4
    for step in range(6):
        w = ht.effective_weight_matrix(step)
        srcs, dsts = np.nonzero(w)
        crossing = [(s, d) for s, d in zip(srcs, dsts)
                    if slice_of[s] != slice_of[d]]
        if ht.is_outer_step(step):
            assert crossing
        else:
            assert not crossing


def test_outer_sweep_is_exact_interslice_average():
    """With 0.5/0.5 weights a full one-peer exp2 sweep over the slices is
    an exact inter-slice average — the property the default self weight
    is chosen for."""
    ht = topo.hierarchical_two_level(16, 4, outer_self_weight=0.5)
    prod = np.eye(16)
    for p in range(len(ht.outer_phases)):
        prod = prod @ ht.outer_full_matrix(p)
    # After the sweep every rank holds the average of its local index
    # across all 4 slices.
    expect = np.kron(np.full((4, 4), 0.25), np.eye(4))
    np.testing.assert_allclose(prod, expect, atol=1e-12)


def test_builder_validation():
    with pytest.raises(ValueError, match="equal slices"):
        topo.hierarchical_two_level(8, 3)
    with pytest.raises(ValueError, match="outer_every"):
        topo.hierarchical_two_level(8, 2, outer_every=0)
    with pytest.raises(ValueError, match="outer_self_weight"):
        topo.hierarchical_two_level(8, 2, outer_self_weight=1.0)
    with pytest.raises(ValueError, match="inner topology"):
        topo.hierarchical_two_level(8, 2, inner="mesh")
    with pytest.raises(ValueError, match="outer walk"):
        topo.hierarchical_two_level(8, 2, outer="star")


def test_product_topology_roundtrip():
    ht = topo.hierarchical_two_level(8, 2, inner="ring")
    g = ht.product_topology(0)
    np.testing.assert_allclose(topo.weight_matrix(g),
                               ht.effective_weight_matrix(0))


# ---------------------------------------------------------------------------
# Executor: dense / cadence / compression vs the matrix oracle
# ---------------------------------------------------------------------------

def _sim_step(ht, x, step, frac=None):
    """Numpy oracle of one hierarchical step (sparse = block-restricted
    outer exchange, matching the compiled executor)."""
    y = ht.inner_full_matrix().T @ x
    if ht.is_outer_step(step):
        outer_step = step // ht.outer_every
        if frac is None:
            wo = ht.outer_full_matrix(ht.outer_phase_index(step))
            y = wo.T @ y
        else:
            size = x.shape[1]
            kk = max(1, int(np.ceil(frac * size)))
            nblocks = -(-size // kk)
            rot = (np.arange(kk) + (outer_step % nblocks) * kk) % size
            wo = ht.outer_full_matrix(
                ht.outer_phase_index(step, sweep_len=nblocks))
            y[:, rot] = wo.T @ y[:, rot]
    return y


def test_dense_cadence1_matches_flat_product():
    """Acceptance: dense/uncompressed/cadence-1 hierarchical gossip is
    equivalent to flat neighbor averaging over the two-level product
    topology <= 1e-6."""
    _env(BLUEFOG_TPU_HIER="1")
    bf.init(lambda: topo.ExponentialGraph(N), local_size=4)
    ht = topo.hierarchical_two_level(N, 2)
    x = _rank_major(1)
    for step in range(3):
        out = np.asarray(bf.hierarchical_gossip(x, step))
        flat = np.asarray(bf.neighbor_allreduce(
            x, src_weights=ht.effective_weight_matrix(step)))
        assert np.abs(out - flat).max() <= 1e-6


def test_cadence_and_phase_switch_executor():
    _env(BLUEFOG_TPU_HIER="1", BLUEFOG_TPU_HIER_OUTER_EVERY="2")
    bf.init(lambda: topo.ExponentialGraph(N), local_size=2)  # 4 slices
    from bluefog_tpu import basics
    ht = basics._hier_topology(basics._ctx)
    assert ht.outer_every == 2 and ht.n_slices == 4
    x = _rank_major(2).astype(np.float64).astype(np.float32)
    X = x.copy()
    for step in range(6):
        out = np.asarray(bf.hierarchical_gossip(X, step))
        expect = _sim_step(ht, X.astype(np.float64), step)
        assert np.abs(out - expect).max() <= 1e-5
        X = out


def test_sparse_outer_executor_matches_oracle():
    _env(BLUEFOG_TPU_HIER="1", BLUEFOG_TPU_HIER_OUTER_EVERY="2",
         BLUEFOG_TPU_HIER_OUTER_COMPRESSION="sparse:0.5")
    bf.init(lambda: topo.ExponentialGraph(N), local_size=4)
    from bluefog_tpu import basics
    ht = basics._hier_topology(basics._ctx)
    x = _rank_major(3)
    X = x.copy()
    for step in range(8):
        out = np.asarray(bf.hierarchical_gossip(X, step))
        expect = _sim_step(ht, X.astype(np.float64), step, frac=0.5)
        assert np.abs(out - expect).max() <= 1e-5
        X = out


def test_bf16_outer_residual():
    """bf16 outer compression: close to the dense result at bf16
    tolerance, and inner-only steps are NOT quantized at all (the codec
    is per-level)."""
    _env(BLUEFOG_TPU_HIER="1", BLUEFOG_TPU_HIER_OUTER_EVERY="2",
         BLUEFOG_TPU_HIER_OUTER_COMPRESSION="bf16")
    bf.init(lambda: topo.ExponentialGraph(N), local_size=4)
    from bluefog_tpu import basics
    ht = basics._hier_topology(basics._ctx)
    x = _rank_major(4)
    out0 = np.asarray(bf.hierarchical_gossip(x, 0))   # outer step
    dense0 = _sim_step(ht, x.astype(np.float64), 0)
    assert np.abs(out0 - dense0).max() <= 2e-2  # bf16-scale error only
    out1 = np.asarray(bf.hierarchical_gossip(x, 1))   # inner-only step
    dense1 = _sim_step(ht, x.astype(np.float64), 1)
    assert np.abs(out1 - dense1).max() <= 1e-6   # untouched by the codec


def test_hier_off_is_bit_identical_and_gated():
    """BLUEFOG_TPU_HIER=0: the hierarchical entry point refuses, and the
    flat path is bit-identical whether the knob is 0, unset or 1."""
    x = _rank_major(5)
    _env()
    bf.init(lambda: topo.ExponentialGraph(N), local_size=4)
    out_unset = np.asarray(bf.neighbor_allreduce(x))
    with pytest.raises(RuntimeError, match="BLUEFOG_TPU_HIER"):
        bf.hierarchical_gossip(x, 0)
    assert bf.hierarchical_gossip_info() is None
    bf.shutdown()
    for knob in ("0", "1"):
        _env(BLUEFOG_TPU_HIER=knob)
        bf.init(lambda: topo.ExponentialGraph(N), local_size=4)
        assert np.array_equal(np.asarray(bf.neighbor_allreduce(x)),
                              out_unset)
        bf.shutdown()


def test_hier_needs_multislice_mesh():
    _env(BLUEFOG_TPU_HIER="1")
    bf.init(lambda: topo.ExponentialGraph(N))  # local_size == n: 1 slice
    with pytest.raises(RuntimeError, match="multi-slice"):
        bf.hierarchical_gossip(_rank_major(6), 0)


def test_per_level_telemetry():
    from bluefog_tpu.utils import telemetry
    _env(BLUEFOG_TPU_HIER="1", BLUEFOG_TPU_HIER_OUTER_EVERY="2",
         BLUEFOG_TPU_HIER_OUTER_COMPRESSION="sparse:0.25")
    telemetry.reset()
    bf.init(lambda: topo.ExponentialGraph(N), local_size=4)
    x = _rank_major(7)
    for step in range(4):  # steps 0, 2 are outer
        bf.hierarchical_gossip(x, step)
    snap = bf.telemetry_snapshot()
    ici = snap['bf_comm_level_bytes_total{level="ici"}']
    dcn = snap['bf_comm_level_bytes_total{level="dcn"}']
    assert snap["bf_hier_outer_steps_total"] == 2.0
    row_bytes = x.nbytes / N
    # inner exp2(4): 2 off-diag offsets -> 8 directed edges per slice pair
    # of slices => 16 rows per step, 4 steps.
    assert ici == pytest.approx(row_bytes * 16 * 4)
    # outer: 8 ranks x 0.25 sparse, on 2 of 4 steps.
    assert dcn == pytest.approx(row_bytes * 8 * 0.25 * 2)
    # And the series are visible on /metrics.
    rendered = telemetry.render_prometheus()
    assert "bf_comm_level_bytes_total" in rendered
    assert "bf_hier_outer_steps_total" in rendered


def test_placement_prices_hier_levels():
    """With a fake multi-slice torus + HIER on, set_topology's placement
    search prices the two levels too (and the executor still matches the
    oracle under the installed placement)."""
    _env(BLUEFOG_TPU_HIER="1", BLUEFOG_TPU_FAKE_TORUS="2x4")
    bf.init(lambda: topo.ExponentialGraph(N), local_size=4)
    assert bf.placement_info() is not None
    ht = topo.hierarchical_two_level(N, 2)
    x = _rank_major(8)
    out = np.asarray(bf.hierarchical_gossip(x, 0))
    expect = ht.effective_weight_matrix(0).T @ x.astype(np.float64)
    assert np.abs(out - expect).max() <= 1e-5


# ---------------------------------------------------------------------------
# Optimizer families
# ---------------------------------------------------------------------------

def test_hier_gossip_optimizer_awc():
    import optax
    _env(BLUEFOG_TPU_HIER="1")
    bf.init(lambda: topo.ExponentialGraph(N), local_size=4)
    ht = topo.hierarchical_two_level(N, 2)
    opt = bf.optim.DistributedHierarchicalGossipOptimizer(optax.sgd(0.1))
    params = {"w": _rank_major(9)}
    grads = {"w": _rank_major(10) * 0.1}
    state = opt.init(params)
    new_params, _ = opt.step(params, grads, state)
    expect = (ht.effective_weight_matrix(0).T
              @ params["w"].astype(np.float64)) - 0.1 * grads["w"]
    assert np.abs(np.asarray(new_params["w"]) - expect).max() <= 1e-5
    # Per-level accounting flowed through the optimizer step too.
    snap = bf.telemetry_snapshot()
    assert snap.get("bf_hier_outer_steps_total", 0) >= 1.0


def test_hier_gossip_optimizer_requires_knob():
    import optax
    _env()
    bf.init(lambda: topo.ExponentialGraph(N), local_size=4)
    opt = bf.optim.DistributedHierarchicalGossipOptimizer(optax.sgd(0.1))
    params = {"w": _rank_major(11)}
    with pytest.raises(RuntimeError, match="BLUEFOG_TPU_HIER"):
        opt.step(params, params, opt.init(params))


def test_window_optimizer_drives_churn_supervisor(monkeypatch):
    """Satellite (PR 7 follow-up): every window-family step() feeds the
    churn supervisor — no manual supervisor.step() in the training loop."""
    import optax

    from bluefog_tpu.run import supervisor as sup_mod

    class _View:
        epoch = 3
        evicted = False

    class _Sup:
        def __init__(self):
            self.steps = []

        def step(self, t):
            self.steps.append(t)
            return _View() if t == 1 else None

    stub = _Sup()
    monkeypatch.setattr(sup_mod, "maybe_supervisor", lambda: stub)
    _env(BLUEFOG_TPU_CHURN="1")
    bf.init(lambda: topo.ExponentialGraph(N))
    opt = bf.optim.DistributedWinPutOptimizer(optax.sgd(0.05))
    params = {"w": _rank_major(12)}
    state = opt.init(params)
    for _ in range(3):
        params, state = opt.step(params, {"w": _rank_major(13)}, state)
    assert stub.steps == [0, 1, 2]
    assert opt.membership_change is not None
    assert opt.membership_change.epoch == 3
    assert not opt.evicted
    opt.free()


def test_window_optimizer_eviction_raises(monkeypatch):
    import optax

    from bluefog_tpu.run import supervisor as sup_mod

    class _View:
        epoch = 5
        evicted = True

    class _Sup:
        def step(self, t):
            return _View()

    monkeypatch.setattr(sup_mod, "maybe_supervisor", lambda: _Sup())
    _env(BLUEFOG_TPU_CHURN="1")
    bf.init(lambda: topo.ExponentialGraph(N))
    opt = bf.optim.DistributedPushSumOptimizer(optax.sgd(0.05))
    params = {"w": _rank_major(14)}
    state = opt.init(params)
    with pytest.raises(RuntimeError, match="evicted"):
        opt.step(params, {"w": _rank_major(15)}, state)
    assert opt.evicted
    opt.free()


def test_window_optimizer_no_churn_no_supervisor():
    """Default (churn off): maybe_supervisor is a cheap no-op — no
    supervisor singleton is ever constructed by the optimizer path."""
    import optax

    from bluefog_tpu.run import supervisor as sup_mod
    _env()
    bf.init(lambda: topo.ExponentialGraph(N))
    opt = bf.optim.DistributedWinPutOptimizer(optax.sgd(0.05))
    params = {"w": _rank_major(16)}
    state = opt.init(params)
    params, state = opt.step(params, {"w": _rank_major(17)}, state)
    assert sup_mod._singleton is None
    assert opt.membership_change is None
    opt.free()


# ---------------------------------------------------------------------------
# sparse:<frac> wire codec (window transport)
# ---------------------------------------------------------------------------

def test_sparse_codec_roundtrip_bit_exact():
    from bluefog_tpu.ops import transport as T
    rng = np.random.default_rng(0)
    row = rng.standard_normal(33).astype(np.float32)
    idx = np.sort(np.argsort(-np.abs(row))[:9]).astype(np.int32)
    payload = T.sparse_encode(row[idx], idx)
    d_idx, d_val = T.sparse_decode(payload)
    assert np.array_equal(d_idx, idx)
    assert np.array_equal(d_val.view(np.int32), row[idx].view(np.int32))


def test_sparse_codec_through_op_batch_framing():
    """Acceptance: sparse:<frac> round-trips BIT-exact through the
    OP_BATCH container framing."""
    from bluefog_tpu.ops import transport as T
    rng = np.random.default_rng(1)
    rows = [rng.standard_normal(16).astype(np.float32) for _ in range(3)]
    msgs = []
    for i, row in enumerate(rows):
        idx = np.sort(np.argsort(-np.abs(row))[:4]).astype(np.int32)
        msgs.append((T.OP_ACCUMULATE | T.OP_SPARSE_FLAG, f"w{i}", 0, 1,
                     0.5, 0.0, T.sparse_encode(row[idx], idx).tobytes()))
    decoded = T._decode_batch(T._encode_batch(msgs))
    assert len(decoded) == len(msgs)
    for (op, name, _s, _d, _w, _p, payload), orig in zip(decoded, msgs):
        assert op & T.OP_SPARSE_FLAG
        assert bytes(payload) == orig[6]
        T.sparse_decode(payload)  # decodes cleanly from the framed view


def test_sparse_codec_rejects_malformed():
    from bluefog_tpu.ops import transport as T
    payload = T.sparse_encode(np.ones(3, np.float32),
                              np.arange(3, dtype=np.int32))
    with pytest.raises(ValueError, match="does not match header"):
        T.sparse_decode(payload.tobytes() + b"\0")
    with pytest.raises(ValueError, match="matching 1-D"):
        T.sparse_encode(np.ones((2, 2), np.float32),
                        np.arange(4, dtype=np.int32))


def test_payload_row_sparse_scatter_and_bounds():
    from bluefog_tpu.ops import transport as T
    from bluefog_tpu.ops import window as W

    class _Win:
        name = "w"
        shape = (6,)
        dtype = np.dtype(np.float32)

    vals = np.asarray([1.5, -2.0], np.float32)
    idx = np.asarray([1, 4], np.int32)
    row = W._payload_row(_Win(), bytes(T.sparse_encode(vals, idx)),
                         sparse=True)
    np.testing.assert_array_equal(
        row, np.asarray([0, 1.5, 0, 0, -2.0, 0], np.float32))
    bad = T.sparse_encode(vals, np.asarray([1, 6], np.int32))
    with pytest.raises(ValueError, match="outside"):
        W._payload_row(_Win(), bytes(bad), sparse=True)


def test_sender_error_feedback_conserves_mass():
    """The EF residual: across consecutive sends on one edge, decoded
    wire mass + the live residual equals the exact input mass — the
    invariant that keeps sparsification bias from breaking consensus."""
    from bluefog_tpu.ops import transport as T
    from bluefog_tpu.ops import window as W
    W._drop_ef_residuals()
    rng = np.random.default_rng(2)
    total_in = np.zeros(16, np.float64)
    total_sent = np.zeros(16, np.float64)
    try:
        for _ in range(5):
            row = rng.standard_normal(16).astype(np.float32)
            total_in += row
            payload = W._sparse_payload("wef", 0, 1, row, 0.25)
            idx, vals = T.sparse_decode(payload)
            assert idx.size == 4  # ceil(0.25 * 16)
            total_sent[idx] += vals
        with W._ef_lock:
            residual = W._ef_residuals[("wef", 0, 1)].astype(np.float64)
        np.testing.assert_allclose(total_sent + residual, total_in,
                                   atol=1e-5)
    finally:
        W._drop_ef_residuals()
    assert ("wef", 0, 1) not in W._ef_residuals


def test_sparse_codec_applies_to_accumulate_only(monkeypatch):
    """The wire codec sparsifies ACCUMULATE edges only: a PUT overwrites
    its staging slot, where a scattered-into-zeros row would zero every
    unsent coordinate — puts (and GET replies) must ship exact."""
    from bluefog_tpu.ops import transport as T
    from bluefog_tpu.ops import window as W

    sent = []

    class _StubTransport:
        def send(self, host, port, op, name, src, dst, weight, payload,
                 p_weight=0.0, stripe=None):
            sent.append((op, np.asarray(payload).copy()))

    class _StubDistrib:
        transport = _StubTransport()
        proc_addr = {0: ("h", 1), 1: ("h", 2)}
        rank_owner = {0: 0, 1: 1}
        my_proc = 0

    monkeypatch.setenv("BLUEFOG_TPU_WIN_COMPRESSION", "sparse:0.25")
    config.reload()
    monkeypatch.setattr(W._store, "distrib", _StubDistrib())
    W._drop_ef_residuals()
    try:
        row = np.arange(16, dtype=np.float32)
        W._send_to_proc(1, T.OP_ACCUMULATE, "w", 0, 1, 1.0, 0.0,
                        payload=row.view(np.uint8).reshape(-1)
                        .view(np.float32))
        W._send_to_proc(1, T.OP_PUT, "w", 0, 1, 1.0, 0.0,
                        payload=row.copy())
        W._send_to_proc(1, T.OP_GET_REPLY, "w", 0, 1, 1.0, 0.0,
                        payload=row.copy())
        (op_acc, p_acc), (op_put, p_put), (op_get, p_get) = sent
        assert op_acc & T.OP_SPARSE_FLAG
        idx, vals = T.sparse_decode(p_acc)
        assert idx.size == 4  # ceil(0.25 * 16)
        assert not op_put & T.OP_SPARSE_FLAG
        assert not op_get & T.OP_SPARSE_FLAG
        np.testing.assert_array_equal(
            p_put.view(np.float32), row)  # exact dense put
    finally:
        W._drop_ef_residuals()
    monkeypatch.delenv("BLUEFOG_TPU_WIN_COMPRESSION")
    config.reload()


def test_single_slice_artifact_is_inner_only():
    """The degenerate n_slices=1 artifact has no outer level: every step
    is the inner operator alone (no IndexError on the empty phase
    table)."""
    ht = topo.hierarchical_two_level(8, 1)
    assert ht.outer_phases == ()
    assert ht.dcn_edges_per_outer_step() == 0
    for step in range(3):
        np.testing.assert_allclose(ht.effective_weight_matrix(step),
                                   ht.inner_full_matrix())


def test_ef_residual_dropped_on_win_free():
    from bluefog_tpu.ops import window as W
    W._drop_ef_residuals()
    with W._ef_lock:
        W._ef_residuals[("a", 0, 1)] = np.zeros(4, np.float32)
        W._ef_residuals[("b", 0, 1)] = np.zeros(4, np.float32)
    W.win_free("a")   # no such window: False, but residuals still purged
    assert ("a", 0, 1) not in W._ef_residuals
    assert ("b", 0, 1) in W._ef_residuals
    W._free_all_windows()
    assert not W._ef_residuals


# ---------------------------------------------------------------------------
# Config vocabulary (satellite: dynamic enumeration)
# ---------------------------------------------------------------------------

def test_compression_vocabulary_accepts_sparse(monkeypatch):
    monkeypatch.setenv("BLUEFOG_TPU_WIN_COMPRESSION", "sparse:0.25")
    config.reload()
    assert config.get().win_compression == "sparse:0.25"
    assert config.parse_sparse_frac("sparse:0.25") == 0.25
    monkeypatch.delenv("BLUEFOG_TPU_WIN_COMPRESSION")
    config.reload()


def test_compression_error_enumerates_vocabulary(monkeypatch):
    monkeypatch.setenv("BLUEFOG_TPU_WIN_COMPRESSION", "fp16")
    with pytest.raises(ValueError) as e:
        config.reload()
    for word in config.COMPRESSION_VOCAB:
        assert word in str(e.value)
    monkeypatch.setenv("BLUEFOG_TPU_WIN_COMPRESSION", "sparse:2.0")
    with pytest.raises(ValueError, match=r"\(0, 1\]"):
        config.reload()
    monkeypatch.setenv("BLUEFOG_TPU_WIN_COMPRESSION", "sparse:x")
    with pytest.raises(ValueError, match="float"):
        config.reload()
    monkeypatch.delenv("BLUEFOG_TPU_WIN_COMPRESSION")
    config.reload()


def test_hier_outer_compression_validated(monkeypatch):
    monkeypatch.setenv("BLUEFOG_TPU_HIER_OUTER_COMPRESSION", "lz4")
    with pytest.raises(ValueError, match="HIER_OUTER_COMPRESSION"):
        config.reload()
    monkeypatch.delenv("BLUEFOG_TPU_HIER_OUTER_COMPRESSION")
    config.reload()


# ---------------------------------------------------------------------------
# Legacy inner/outer dynamic generators (satellite: structural coverage)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("gen_name,world,local", [
    ("GetInnerOuterRingDynamicSendRecvRanks", 12, 4),
    ("GetInnerOuterRingDynamicSendRecvRanks", 16, 4),
    ("GetInnerOuterExpo2DynamicSendRecvRanks", 24, 6),
    ("GetInnerOuterExpo2DynamicSendRecvRanks", 32, 8),
])
def test_inner_outer_walk_structure(gen_name, world, local):
    """Structure the consistency tests don't pin down: exactly one local
    rank per machine (``step % local``) crosses machines each step — to
    the SAME local slot of another machine — while every other rank walks
    strictly inside its machine and never targets the outgoing rank."""
    gen = getattr(topo, gen_name)
    walkers = [gen(world, local, r) for r in range(world)]
    machines = world // local
    for step in range(2 * local):
        outgoing_local = step % local
        sends = [next(w)[0][0] for w in walkers]
        for r, s in enumerate(sends):
            m, i = divmod(r, local)
            sm, si = divmod(s, local)
            if i == outgoing_local:
                # The designated rank hops machines, same local slot.
                assert sm != m and si == i
            else:
                # Everyone else stays home and detours around the
                # outgoing rank.
                assert sm == m and si != outgoing_local and s != r


def test_inner_outer_ring_inner_distance():
    """Ring inner walk: the stay-home ranks advance by exactly one local
    position (after skipping over the outgoing slot)."""
    world, local = 12, 4
    walkers = [topo.GetInnerOuterRingDynamicSendRecvRanks(world, local, r)
               for r in range(world)]
    for step in range(local):
        outgoing_local = step % local
        sends = [next(w)[0][0] for w in walkers]
        for r, s in enumerate(sends):
            m, i = divmod(r, local)
            if i == outgoing_local:
                continue
            fwd = 1
            if fwd >= (outgoing_local - i) % local:
                fwd += 1
            assert s == m * local + (i + fwd) % local


def test_inner_outer_expo2_outer_distances_cycle():
    """The outgoing rank's machine hop follows the Exp2 distance ladder
    2**(step % ceil(log2(machines-1)))."""
    world, local = 32, 4  # 8 machines
    machines = world // local
    outer_n = int(np.log2(machines - 1)) + 1
    walkers = [topo.GetInnerOuterExpo2DynamicSendRecvRanks(world, local, r)
               for r in range(world)]
    for step in range(2 * outer_n * local):
        sends = [next(w)[0][0] for w in walkers]
        outgoing_local = step % local
        d = 2 ** (step % outer_n)
        for m in range(machines):
            r = m * local + outgoing_local
            expect = ((m + d) % machines) * local + outgoing_local
            assert sends[r] == expect
