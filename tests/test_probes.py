"""In-program probes (BLUEFOG_TPU_PROBE, ``utils/probes.py``).

Covers the probe tentpole's contract surface:

  * the native ring ABI: drain order, the shared
    ``steady_clock == time.monotonic_ns()`` clock domain, and wraparound
    (an over-full ring keeps exactly the newest ``capacity`` events
    while ``total()`` still counts everything ever claimed);
  * ``BLUEFOG_TPU_PROBE=0`` inertness: no probe op is compiled into the
    fused program, the ring never records, no probe metric registers —
    and ``BLUEFOG_TPU_TELEMETRY=0`` keeps the registry untouched even
    with probes firing;
  * real fused-path phase attribution: a fused step inside
    ``bf.step_profile()`` reports non-zero gossip-communicate AND
    optimizer-update (the acceptance criterion — pre-probe, the whole
    program booked as grad-compute), in loose agreement with the eager
    leg's span-hook attribution, and never the degraded ``fused-step``
    label while probes reconcile;
  * the trace surface: two synthesized ranks' probe lanes
    (cat ``fused-probe``, tids 998/999/1000+bucket) survive
    ``tools trace-merge`` into per-rank process lanes.
"""

import json
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

import bluefog_tpu as bf
from bluefog_tpu import native
from bluefog_tpu import topology as topo
from bluefog_tpu.ops import xlaffi
from bluefog_tpu.optim import window_optimizers as WO
from bluefog_tpu.utils import (config, probes, profiler, telemetry,
                               timeline)

needs_probe = pytest.mark.skipif(
    not (native.available() and native.has_probe()),
    reason="native core lacks the bf_probe_* ring")

needs_fused = pytest.mark.skipif(
    not (native.available() and native.has_win_xla()
         and native.has_xla_handler() and xlaffi.has_passthrough()),
    reason="native core lacks the bf_xla_win_put_pass XLA handler")


@pytest.fixture
def probe_env(monkeypatch):
    """Env knobs + a pristine probe ring / registry / profiler before AND
    after (probe state is process-wide — a test must not leak armed rings
    or degraded flags into the next one)."""
    def set_env(**kv):
        for k, v in kv.items():
            monkeypatch.setenv(k, str(v))
        config.reload()
        xlaffi._reset_for_tests()
    probes._reset_for_tests()
    telemetry.reset()
    profiler._reset_for_tests()
    yield set_env
    config.reload()
    xlaffi._reset_for_tests()
    probes._reset_for_tests()
    telemetry.reset()
    profiler._reset_for_tests()


# ---------------------------------------------------------------------------
# Ring ABI
# ---------------------------------------------------------------------------

@needs_probe
def test_ring_drain_order_and_shared_clock(probe_env):
    """Events drain oldest-first with contiguous sequence numbers, on the
    same CLOCK_MONOTONIC domain as ``time.monotonic_ns()`` — the property
    the reconciler and the timeline lanes both lean on."""
    assert probes.arm()
    ids = [probes.GRAD_READY, probes.BUCKET_PRE, probes.BUCKET_POST,
           probes.STEP_END, probes.DRAIN_START]
    t0 = time.monotonic_ns()
    for pid in ids:
        probes.note(pid)
    t1 = time.monotonic_ns()
    ev = probes.drain()
    assert [pid for _t, pid, _s in ev] == ids
    assert [s for _t, _p, s in ev] == list(range(len(ids)))
    ts = [t for t, _p, _s in ev]
    assert ts == sorted(ts)
    # Same clock domain: every stamp falls inside the host-side bracket.
    assert t0 <= ts[0] and ts[-1] <= t1, (t0, ts, t1)
    assert probes.drain() == [], "a second drain must be empty"


@needs_probe
def test_ring_wraparound_keeps_newest(probe_env):
    """An over-full ring loses the OLDEST events: noting capacity+50
    events drains exactly ``capacity`` with the newest sequence numbers,
    while ``total()`` still counts every claim (the lost-count signal)."""
    assert probes.arm()
    cap = int(native.lib().bf_probe_enable(0))  # existing ring's capacity
    extra = 50
    for _ in range(cap + extra):
        probes.note(probes.GRAD_READY)
    assert probes.total() == cap + extra
    ev = probes.drain(cap=cap + extra)
    assert len(ev) == cap, "exactly the newest capacity events survive"
    seqs = [s for _t, _p, s in ev]
    assert seqs == list(range(extra, cap + extra)), \
        (seqs[0], seqs[-1], cap, extra)


# ---------------------------------------------------------------------------
# Inertness gates
# ---------------------------------------------------------------------------

def _params():
    return {
        "b": jnp.asarray(np.random.RandomState(1).randn(8, 20)
                         .astype(np.float32)),
        "w": jnp.asarray(np.random.RandomState(0).randn(8, 4, 3)
                         .astype(np.float32)),
    }


def _grad_stream(params, steps, seed=42):
    rng = np.random.RandomState(seed)
    return [jax.tree.map(
        lambda x: x * 0.01 + jnp.asarray(
            rng.randn(*x.shape).astype(np.float32)) * 1e-3, params)
        for _ in range(steps)]


def _run_fused(steps=2, profile=False):
    """The plain fused rig (no loopback wire — puts run against the local
    store); returns (opt, per-step profiler phase dicts)."""
    bf.init(lambda: topo.RingGraph(8))
    params = _params()
    opt = WO.DistributedWinPutOptimizer(optax.sgd(0.5), fused=True,
                                        fusion_buckets=2)
    st = opt.init(params)
    phases = []
    try:
        p = params
        for g in _grad_stream(params, steps):
            if profile:
                with bf.step_profile(straggler=False) as prof:
                    p, st = opt.step(p, g, st, require_mutex=False)
                phases.append(prof.phases())
            else:
                p, st = opt.step(p, g, st, require_mutex=False)
        assert opt._fused_impl is not None
        assert opt._fused_impl.fused_steps == steps
        return opt, phases
    finally:
        opt.free()


@needs_fused
@needs_probe
def test_probe_env_off_is_bitwise_inert(probe_env):
    """``BLUEFOG_TPU_PROBE=0`` compiles NO probe ops (the cached program
    says so), never arms the ring, and registers no probe metric — the
    fused program is the pre-probe lowering."""
    probe_env(BLUEFOG_TPU_PROBE=0)
    assert config.get().probe is False
    opt, _ = _run_fused(steps=2)
    assert all(not prog.probes
               for prog in opt._fused_impl._programs.values()), \
        "=0 must compile probe-free programs"
    assert probes.total() == 0, "the ring must never record at =0"
    snap = telemetry.snapshot()
    bad = [k for k in snap
           if k.startswith(("bf_probe_", "bf_fused_overlap",
                            "bf_fused_bucket"))]
    assert not bad, bad


@needs_fused
@needs_probe
def test_telemetry_off_keeps_registry_untouched(probe_env):
    """Probes ON + ``BLUEFOG_TPU_TELEMETRY=0``: the ring records and the
    program carries probe ops, but reconcile mutates NO metric — the
    registry stays byte-empty like every other telemetry source."""
    probe_env(BLUEFOG_TPU_TELEMETRY=0)
    opt, _ = _run_fused(steps=2)
    assert any(prog.probes
               for prog in opt._fused_impl._programs.values())
    assert telemetry.snapshot() == {}, \
        "TELEMETRY=0 must keep the registry empty"


# ---------------------------------------------------------------------------
# Phase attribution (the acceptance criterion)
# ---------------------------------------------------------------------------

@needs_fused
@needs_probe
def test_fused_profile_reports_real_phases(probe_env):
    """With probes on (the default), a fused step inside
    ``bf.step_profile()`` reports non-zero gossip-communicate AND
    optimizer-update — the program is no longer booked wholesale to
    grad-compute, and the degraded ``fused-step`` label never appears."""
    probe_env(BLUEFOG_TPU_PROBE=1)
    _opt, phases = _run_fused(steps=3, profile=True)
    for ph in phases[1:]:  # step 0 is compile-dominated
        assert ph.get("gossip-communicate", 0.0) > 0.0, ph
        assert ph.get("optimizer-update", 0.0) > 0.0, ph
        assert profiler.FUSED_PHASE not in ph, ph
    assert not profiler.attribution_degraded()
    s = probes.last_summary()
    assert s is not None and s["attributed"]
    assert 0.0 < s["measured_overlap"] <= 1.0
    assert len(s["bucket_issue_seconds"]) == 2
    snap = telemetry.snapshot()
    assert snap.get("bf_probe_events_total", 0) > 0
    assert 0.0 < snap.get("bf_fused_overlap_ratio", 0) <= 1.0


@needs_fused
@needs_probe
def test_fused_vs_eager_attribution_agreement(probe_env):
    """The fused leg's probe-derived communication share loosely agrees
    with the eager leg's span-hook share: same non-zero phase set, and
    the gossip-communicate fractions within a wide factor of each other
    (CPU loopback noise — this guards against gross misattribution like
    booking the drain into optimizer-update, not against jitter)."""
    probe_env(BLUEFOG_TPU_PROBE=1)
    _, fused_ph = _run_fused(steps=4, profile=True)
    from bluefog_tpu import basics
    basics._reset_for_tests()
    bf.init(lambda: topo.RingGraph(8))
    params = _params()
    opt = WO.DistributedWinPutOptimizer(optax.sgd(0.5), fusion_buckets=2)
    st = opt.init(params)
    eager_ph = []
    try:
        p = params
        for g in _grad_stream(params, 4):
            with bf.step_profile(straggler=False) as prof:
                p, st = opt.step(p, g, st, require_mutex=False)
            eager_ph.append(prof.phases())
    finally:
        opt.free()

    def comm_frac(rows):
        rows = rows[1:]  # drop the compile-dominated first step
        f = [r.get("gossip-communicate", 0.0) / max(sum(r.values()), 1e-12)
             for r in rows]
        return sum(f) / len(f)

    cf, ce = comm_frac(fused_ph), comm_frac(eager_ph)
    assert cf > 0.0 and ce > 0.0, (cf, ce)
    ratio = max(cf, ce) / min(cf, ce)
    assert ratio < 10.0, \
        f"fused comm share {cf:.3f} vs eager {ce:.3f} (x{ratio:.1f})"


# ---------------------------------------------------------------------------
# Trace surface
# ---------------------------------------------------------------------------

def test_two_rank_trace_merge_probe_lanes(probe_env, tmp_path,
                                          monkeypatch):
    """Probe lanes from two ranks merge into per-rank process lanes:
    synthesize each rank's timeline with ``probe_span``/``thread_name``
    (exactly what ``probes._emit_lanes`` emits) and assert trace-merge
    keeps the ``fused-probe`` category, the synthetic tids and the lane
    names under pid 0 and pid 1."""
    monkeypatch.setenv("BLUEFOG_TPU_PYTHON_TIMELINE", "1")
    config.reload()
    prefix = str(tmp_path / "tl_")
    for rank in (0, 1):
        assert timeline.start_timeline(f"{prefix}{rank}.json")
        base_us = time.monotonic_ns() // 1000
        timeline.probe_span("fused-step", base_us, 900, 999)
        timeline.thread_name(999, "fused fused-step")
        timeline.probe_span("drain", base_us + 900, 120, 998)
        for bi in range(2):
            timeline.probe_span(f"bucket{bi} put-issue",
                                base_us + 100 * (bi + 1), 80, 1000 + bi)
        timeline.stop_timeline()
    from bluefog_tpu import tools
    out = tools.trace_merge(prefix)
    events, _repaired = tools.load_trace_events(out)
    for rank in (0, 1):
        lanes = [e for e in events
                 if e.get("pid") == rank and e.get("cat") == "fused-probe"]
        assert {e["tid"] for e in lanes} == {998, 999, 1000, 1001}, \
            (rank, lanes)
        assert all(e.get("ph") == "X" and e.get("dur", 0) >= 0
                   for e in lanes)
        names = [e for e in events
                 if e.get("pid") == rank and e.get("ph") == "M"
                 and e.get("name") == "thread_name"
                 and e.get("args", {}).get("name") == "fused fused-step"]
        assert names, "the synthetic lane name must survive the merge"
    # The merged doc is valid chrome-tracing JSON end to end.
    with open(out) as f:
        json.load(f)
