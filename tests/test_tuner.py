"""Self-tuning comm control plane (utils/tuner.py + MeasuredModel).

Covers the tentpole's contract surface:
  * BLUEFOG_TPU_TUNE=0 => bitwise inert: no tuner exists, no bf_tune_*
    series registers, no health block, and every override read site
    passes the configured default through untouched;
  * resolve_stripes both ways: the static oracle is authoritative with
    TUNE off, the tuner's measured derivation overrides it when armed,
    and an explicit BLUEFOG_TPU_WIN_STRIPES always wins;
  * cross-rank determinism: hermetic "ranks" fed PERMUTED link snapshots
    derive byte-identical MeasuredModels (canonical_bytes), equal
    sketches, provenance measured:<sketch>, and identical re-priced
    edge costs through the active-placement path;
  * the hysteresis state machine with a fake clock (injected counts_fn
    and synthetic step numbers): divergence trigger, exactly one epoch
    per change, dwell/probation gating, commit vs revert-on-regression,
    and post-revert pinning;
  * the tools-top tune column and the bench-trend MULTICHIP table.
"""

import json

import pytest

from bluefog_tpu import tools as toolsmod
from bluefog_tpu.ops import placement as PL
from bluefog_tpu.ops import transport as T
from bluefog_tpu.tools import top as topmod
from bluefog_tpu.utils import config, linkobs, telemetry, tuner


@pytest.fixture
def tune_env(monkeypatch):
    """Set knobs + reload config; tuner, registry and the active
    placement start and end clean."""
    def set_env(**kv):
        for k, v in kv.items():
            if v is None:
                monkeypatch.delenv(k, raising=False)
            else:
                monkeypatch.setenv(k, str(v))
        config.reload()
    prev_active = PL.active()
    telemetry.reset()
    tuner.reset()
    yield set_env
    PL.set_active(*(prev_active if prev_active is not None
                    else (None, None)))
    tuner.reset()
    telemetry.reset()
    config.reload()


def _snap(edges):
    """A bf_link_* snapshot in the registry's rendered-key form."""
    return {f'bf_link_delay_us{{src="{s}",dst="{d}"}}': float(us)
            for s, d, us in edges}


# One rank's outbound data links held hot (the linkdelay fault shape):
# every edge out of rank 1 at 60 ms, everything else at loopback noise.
_HOT = _snap([(1, 0, 60_000.0), (1, 2, 60_000.0),
              (0, 1, 200.0), (2, 1, 210.0), (0, 2, 205.0)])


def _hermetic_tuner(**kw):
    """A Tuner whose adaptation side effects stay inside the instance:
    no live re-plan (basics may be initialized by OTHER tests in this
    process), no placement model, no live transport pokes."""
    t = tuner.Tuner(**kw)
    t._replan = lambda rel: (None, False, None)
    t._base_model = lambda: None
    t._live_transports = lambda: []
    return t


# -- fake clock: synthetic bf_optimizer_step_seconds bucket counts -------

_B = list(telemetry._HIST_BUCKETS)


def _counts(idx, n):
    c = [0.0] * (len(_B) + 1)
    c[idx] = float(n)
    return c


def _add(a, b):
    return [x + y for x, y in zip(a, b)]


_FAST, _SLOW = 2, len(_B) - 2     # well-separated bucket indices


# ---------------------------------------------------------------------------
# Off-switch: bitwise inert
# ---------------------------------------------------------------------------

def test_tune_off_is_inert(tune_env):
    tune_env(BLUEFOG_TPU_TUNE=None)
    assert not config.get().tune
    assert tuner.maybe_tuner() is None
    tuner.feed_snapshots([_HOT])
    tuner.tick(5)
    assert tuner.health_summary() is None
    # Not one bf_tune_* series — nothing registered at all.
    assert telemetry.snapshot() == {}
    # Every override read site passes the default through untouched.
    assert tuner.override_int("stripes", 3) == 3
    assert tuner.override_int("hier_outer_every", 7) == 7
    assert tuner.override_float("sparse_frac", 0.25) == 0.25
    assert tuner.override_float("coalesce_linger_ms", 2.5) == 2.5


def test_tune_off_explicit_zero(tune_env):
    tune_env(BLUEFOG_TPU_TUNE="0")
    assert tuner.maybe_tuner() is None
    assert tuner.health_summary() is None


def test_maybe_measured_gates(tune_env):
    tune_env(BLUEFOG_TPU_TUNE="1")
    base = PL.TorusModel("torus", (2, 2), tuple(range(8)), n_slices=2)
    measured = PL.MeasuredModel.from_measurements(
        base, [(0, 1, 2.0)], dcn_link_cost=6.0)
    tuner._measured_model = measured
    assert tuner.maybe_measured(base) is measured
    # Geometry mismatch: the stale model never re-prices a new mesh.
    other = PL.TorusModel("torus", (4,), tuple(range(4)))
    assert tuner.maybe_measured(other) is other
    # TUNE=0: the argument comes back untouched even with state present.
    tune_env(BLUEFOG_TPU_TUNE="0")
    assert tuner.maybe_measured(base) is base


# ---------------------------------------------------------------------------
# resolve_stripes: static oracle vs measured override
# ---------------------------------------------------------------------------

def test_resolve_stripes_static_is_the_tune_off_path(tune_env):
    tune_env(BLUEFOG_TPU_TUNE=None, BLUEFOG_TPU_WIN_STRIPES=None)
    # No model on a plain test process: static auto derives 1, and the
    # tuned resolver agrees bitwise with the override table empty.
    assert T.resolve_stripes_static() == 1
    assert T.resolve_stripes() == T.resolve_stripes_static() == 1


def test_resolve_stripes_explicit_env_wins(tune_env):
    tune_env(BLUEFOG_TPU_TUNE="1", BLUEFOG_TPU_WIN_STRIPES="3")
    tuner._set_override("stripes", 6.0)
    assert T.resolve_stripes() == 3    # explicit config beats the tuner


def test_resolve_stripes_measured_override(tune_env):
    tune_env(BLUEFOG_TPU_TUNE="1", BLUEFOG_TPU_WIN_STRIPES=None)
    assert T.resolve_stripes() == 1
    tuner._set_override("stripes", 4.0)
    assert T.resolve_stripes() == 4
    assert T.resolve_stripes_static() == 1   # the oracle is untouched
    tuner._set_override("stripes", None)
    assert T.resolve_stripes() == 1


# ---------------------------------------------------------------------------
# Cross-rank determinism: permuted snapshots -> byte-identical models
# ---------------------------------------------------------------------------

def _rel_costs(t):
    return t._relative_costs(linkobs.report_from_snapshot(t._matrix))


def test_measured_model_cross_rank_determinism(tune_env):
    tune_env(BLUEFOG_TPU_TUNE="1")
    base = PL.TorusModel("torus", (2, 2), tuple(range(8)), n_slices=2)
    per_rank = [
        _snap([(1, 0, 60_000.0), (0, 2, 205.0)]),
        _snap([(1, 2, 60_000.0), (2, 1, 210.0)]),
        _snap([(0, 1, 200.0)]),
    ]
    perms = [per_rank,
             [per_rank[2], per_rank[0], per_rank[1]],
             [per_rank[1], per_rank[2], per_rank[0]]]
    models = []
    for order in perms:
        t = tuner.Tuner(counts_fn=lambda: None)
        t.feed(order)
        rel = _rel_costs(t)
        models.append(PL.MeasuredModel.from_measurements(
            base, [(s, d, c) for (s, d), c in rel.items()],
            dcn_link_cost=7.7))
    blobs = {m.canonical_bytes() for m in models}
    assert len(blobs) == 1                       # byte-identical
    sketches = {m.sketch for m in models}
    assert len(sketches) == 1
    m = models[0]
    assert m.name == f"measured:{m.sketch}"       # provenance
    # Identical re-priced artifacts through the active-placement path.
    priced = []
    for mm in models:
        PL.set_active(mm, None)
        priced.append({(s, d): PL.predicted_edge_cost(s, d)
                       for s in range(3) for d in range(3) if s != d})
    assert priced[0] == priced[1] == priced[2]
    # The measured edges outrank routed distance; the hot edge carries
    # its measured relative price.
    assert priced[0][(1, 0)] == pytest.approx(60_000.0 / 200.0)
    # The measured DCN price re-prices every inherited consumer.
    assert m.link_weights[m.first_dcn_link] == pytest.approx(7.7)
    # Idempotent re-price: measuring FROM the measured model with the
    # same matrix reproduces the same sketch (no provenance chains).
    again = PL.MeasuredModel.from_measurements(
        m, list(m.edge_cost), dcn_link_cost=m.dcn_link_cost)
    assert again.sketch == m.sketch


# ---------------------------------------------------------------------------
# Hysteresis state machine (fake clock: injected counts_fn + step numbers)
# ---------------------------------------------------------------------------

def test_adapt_exactly_one_epoch_per_change(tune_env):
    tune_env(BLUEFOG_TPU_TUNE="1", BLUEFOG_TPU_TUNE_DWELL_STEPS="5",
             BLUEFOG_TPU_TUNE_DIVERGENCE="3")
    holder = {"c": _counts(_FAST, 10)}
    t = _hermetic_tuner(counts_fn=lambda: list(holder["c"]))
    t.feed([_HOT])
    assert t.max_divergence() > 3.0
    t.on_step(10)
    assert t.epoch == 1
    assert t.last_knob == "coalesce_linger_ms"
    assert t.health()["probation"] is True
    # Probation gates a second epoch even while divergence is high.
    t.on_step(12)
    assert t.epoch == 1
    # Probation settles at 15; same-bucket counts -> commit, no revert.
    t.on_step(15)
    assert t.health()["probation"] is False
    assert t.reverts == 0
    # The applied prices now ARE the measured matrix: divergence settles
    # and the unchanged fault never opens another epoch.
    assert t.max_divergence() == pytest.approx(1.0)
    for s in range(16, 60):
        t.on_step(s)
    assert t.epoch == 1
    # The adapted knob reached its consumers through the override table.
    assert tuner.override_float("coalesce_linger_ms", 0.0) == \
        t.knobs["coalesce_linger_ms"].value > 0.0
    snap = telemetry.snapshot()
    assert snap["bf_tune_epoch"] == 1.0
    assert snap["bf_tune_probation"] == 0.0
    assert snap['bf_tune_adaptations_total{knob="coalesce_linger_ms"}'] \
        == 1.0


def test_changed_matrix_opens_a_new_epoch_after_dwell(tune_env):
    tune_env(BLUEFOG_TPU_TUNE="1", BLUEFOG_TPU_TUNE_DWELL_STEPS="5",
             BLUEFOG_TPU_TUNE_DIVERGENCE="3")
    holder = {"c": _counts(_FAST, 10)}
    t = _hermetic_tuner(counts_fn=lambda: list(holder["c"]))
    # A measurement-DEPENDENT target (like the stripes derivation on a
    # modeled gang), so a changed matrix maps to a changed decision —
    # the built-in linger/staleness targets are deliberately constant
    # per fault shape, which the exactly-one-epoch test covers.
    t._targets = lambda rel, cfg: {
        "coalesce_linger_ms": min(16.0, max(rel.values()) / 100.0)}
    t.feed([_HOT])
    t.on_step(10)
    assert t.epoch == 1
    first = t.knobs["coalesce_linger_ms"].value
    # A DIFFERENT fault (5x hotter) lands mid-probation: gated...
    hotter = _snap([(1, 0, 300_000.0), (1, 2, 300_000.0),
                    (0, 1, 200.0), (2, 1, 210.0), (0, 2, 205.0)])
    t.feed([hotter])
    t.on_step(12)
    assert t.epoch == 1
    t.on_step(14)
    assert t.epoch == 1
    # ...until probation settles and the dwell window has passed — then
    # the new change gets its own numbered epoch and a new bounded move.
    t.on_step(15)
    assert t.epoch == 2
    assert t.knobs["coalesce_linger_ms"].value > first


def test_revert_on_regression_and_pin(tune_env):
    tune_env(BLUEFOG_TPU_TUNE="1", BLUEFOG_TPU_TUNE_DWELL_STEPS="5",
             BLUEFOG_TPU_TUNE_DIVERGENCE="3")
    holder = {"c": _counts(_FAST, 10)}
    t = _hermetic_tuner(counts_fn=lambda: list(holder["c"]))
    base = t.knobs["coalesce_linger_ms"].value   # the configured value
    t.feed([_HOT])
    t.on_step(10)
    assert t.epoch == 1
    moved = t.knobs["coalesce_linger_ms"].value
    assert moved > base
    # The probation window's NEW observations land in a slow bucket:
    # the step-seconds median regressed past 1.25x -> roll back.
    holder["c"] = _add(holder["c"], _counts(_SLOW, 10))
    t.on_step(15)
    assert t.reverts == 1
    assert t.epoch == 2                    # a revert is a numbered epoch
    assert t.last_knob == "revert"
    k = t.knobs["coalesce_linger_ms"]
    assert k.value == base                 # restored
    assert k.pinned_until == 15 + 4 * 5    # _PIN_DWELLS * dwell
    assert tuner.override_float("coalesce_linger_ms", 99.0) == base
    snap = telemetry.snapshot()
    assert snap['bf_tune_reverts_total{knob="coalesce_linger_ms"}'] == 1.0
    # The fault still diverges (applied prices were cleared), but the
    # pinned knob cannot move: no epoch until the pin expires.
    assert t.max_divergence() > 3.0
    t.on_step(21)
    assert t.epoch == 2
    t.on_step(35)                          # pin expired (not > 35)
    assert t.epoch == 3
    assert t.knobs["coalesce_linger_ms"].value > base


def test_bucket_median_delta_semantics():
    # Median of the observations BETWEEN two cumulative snapshots: the
    # old fast samples must not dilute the probation window's medians.
    pre = _counts(_FAST, 10)
    post = _add(pre, _counts(_SLOW, 10))
    med_all = tuner._bucket_median(None, post)
    med_new = tuner._bucket_median(pre, post)
    lo = _B[_SLOW - 1]
    assert med_new > lo                      # inside the slow bucket
    assert med_new > med_all                 # delta, not cumulative
    assert tuner._bucket_median(pre, list(pre)) is None   # no samples
    assert tuner._bucket_median(None, None) is None


def test_no_epoch_without_divergence(tune_env):
    tune_env(BLUEFOG_TPU_TUNE="1", BLUEFOG_TPU_TUNE_DIVERGENCE="3")
    t = _hermetic_tuner(counts_fn=lambda: None)
    flat = _snap([(0, 1, 200.0), (1, 0, 210.0), (2, 1, 205.0)])
    t.feed([flat])
    assert t.max_divergence() < 3.0
    for s in range(50):
        t.on_step(s)
    assert t.epoch == 0
    assert tuner.override_float("coalesce_linger_ms", 1.5) == 1.5


# ---------------------------------------------------------------------------
# Surfaces: /healthz block, tools top column, bench-trend table
# ---------------------------------------------------------------------------

def test_health_summary_armed_vs_off(tune_env):
    tune_env(BLUEFOG_TPU_TUNE="1")
    assert tuner.health_summary() is None    # armed but never constructed
    t = tuner.maybe_tuner()
    assert t is not None
    h = tuner.health_summary()
    assert h == {"epoch": 0, "reverts": 0, "last_knob": None,
                 "probation": False, "max_divergence_ratio": 0.0,
                 "knobs": {k.name: k.value for k in t.knobs.values()},
                 "model": None, "topology": None}


def test_top_tune_column(tune_env):
    tune_env(BLUEFOG_TPU_TUNE=None)
    health = {"status": "ok",
              "tuner": {"epoch": 1, "last_knob": "topology=ring+1",
                        "probation": True}}
    frame = topmod.render_frame({"h:1": ({"bf_x": 1.0}, health)})
    row = next(line for line in frame.splitlines()
               if line.startswith("h:1"))
    assert "tune" in frame                   # the header column
    # Truncated to the cell, with the probation flag surviving.
    assert "1:topology=ri!" in row
    # No tuner block, no gauge: the column renders "-".
    frame_off = topmod.render_frame(
        {"h:2": ({"bf_x": 1.0}, {"status": "ok"})})
    row_off = next(line for line in frame_off.splitlines()
                   if line.startswith("h:2"))
    assert " - " in row_off
    # Health scrape lost, gauge present: the epoch still renders.
    frame_g = topmod.render_frame(
        {"h:3": ({"bf_tune_epoch": 2.0}, None)})
    row_g = next(line for line in frame_g.splitlines()
                 if line.startswith("h:3"))
    assert " 2 " in row_g


def test_bench_trend_multichip_table(tmp_path):
    (tmp_path / "MULTICHIP_r01.json").write_text(json.dumps(
        {"round": 1, "rc": 0, "n_devices": 8, "ok": True}))
    (tmp_path / "MULTICHIP_r02.json").write_text(json.dumps(
        {"round": 2, "rc": 0, "skipped": "no second chip"}))
    lines = toolsmod._multichip_trend(str(tmp_path))
    body = "\n".join(lines)
    assert "round" in lines[0] and "result" in lines[0]
    assert "ok" in body and "skip" in body
    # And the combined bench-trend report carries the table.
    report = toolsmod.bench_trend(str(tmp_path))
    assert "MULTICHIP" in report or "ok" in report
