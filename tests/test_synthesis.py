"""Sketch-guided schedule-synthesis tests (``ops/synthesis.py``) and the
``CompiledSchedule`` artifact refactor.

The invariants pinned here mirror the tentpole's acceptance criteria:

  * every synthesized schedule encodes the BIT-identical effective weight
    matrix (grouping changes, edges and weights never do), emits valid
    partial-permutation rounds, and never exceeds the round budget;
  * synthesis is deterministic — no RNG anywhere — so every SPMD process
    (here: a fresh subprocess) materializes the identical artifact;
  * the packed-vs-synthesized selection strictly beats
    ``congestion_aware_repack`` on modeled ``serial_link_time`` for exp2
    and random-regular(4) on the simulated 8x8 torus and random-regular
    on the 4-slice torus, and is NEVER worse anywhere — where it ties on
    those families, the packed schedule already sits on the provable
    busiest-link-total lower bound;
  * ``BLUEFOG_TPU_SCHEDULE_SYNTH=0`` restores the PR-5 dispatch path
    exactly, and the context schedule cache keys carry the synthesis
    path tag so a mid-process toggle can never serve a stale-path
    schedule.
"""

import math
import os
import subprocess
import sys

import numpy as np
import pytest

import bluefog_tpu as bf
from bluefog_tpu import basics, topology as topo
from bluefog_tpu.ops import collective as C
from bluefog_tpu.ops import placement as PL
from bluefog_tpu.ops import schedule as S
from bluefog_tpu.ops import schedule_opt as SO
from bluefog_tpu.ops import synthesis as SY
from bluefog_tpu.utils import config, telemetry

N = 8  # virtual mesh size (conftest)

_KNOBS = ("BLUEFOG_TPU_SCHEDULE_SYNTH", "BLUEFOG_TPU_SCHEDULE_SYNTH_SKETCH",
          "BLUEFOG_TPU_FAKE_TORUS", "BLUEFOG_TPU_PLACEMENT",
          "BLUEFOG_TPU_PLACEMENT_ROUND_BUDGET")


@pytest.fixture(autouse=True)
def _restore_env():
    saved = {k: os.environ.get(k) for k in _KNOBS}
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    config.reload()
    PL.set_active(None, None)
    SY.clear_synth_cache()


def _env(**kw):
    for k in _KNOBS:
        os.environ.pop(k, None)
    os.environ.update(kw)
    config.reload()


def effective_matrix(sched) -> np.ndarray:
    w = np.diag(np.asarray(sched.self_scale, dtype=float))
    for rnd in sched.rounds:
        for s, d in rnd.pairs:
            assert w[s, d] == 0.0, f"duplicate edge ({s}, {d})"
            w[s, d] = rnd.send_scale[s]
    return w


def assert_valid_rounds(sched):
    for rnd in sched.rounds:
        srcs = [s for s, _ in rnd.pairs]
        dsts = [d for _, d in rnd.pairs]
        assert len(set(srcs)) == len(srcs), "src repeated within a round"
        assert len(set(dsts)) == len(dsts), "dst repeated within a round"
        for s, d in rnd.pairs:
            assert rnd.send_scale[s] != 0.0
            assert rnd.recv_mask[d] == 1.0
            assert rnd.src_of[d] == s


def lower_bound(model, sched, perm=None) -> float:
    # Intentionally independent re-implementation of
    # synthesis.serial_lower_bound: the oracle must not share code with
    # the bound the synthesizer's cap ladder aims at.
    node = np.asarray(model.device_node, np.int64)
    if perm is None:
        perm = np.arange(len(node))
    tot = np.zeros(model.n_links)
    for rnd in sched.rounds:
        for s, d in rnd.pairs:
            r = model.route(int(node[perm[s]]), int(node[perm[d]]))
            np.add.at(tot, r, 1.0)
    return float((tot * model.link_weights).max())


# ---------------------------------------------------------------------------
# CompiledSchedule artifact
# ---------------------------------------------------------------------------

def test_compiled_schedule_artifact_fields_and_provenance():
    w = topo.weight_matrix(topo.RandomRegularGraph(16, 4, seed=0))
    naive = S._build_schedule(w, optimize=False)
    assert isinstance(naive, S.CompiledSchedule)
    assert isinstance(naive, S.StaticSchedule)  # executors keep working
    assert naive.provenance == "naive"
    assert naive.lowering == "ppermute" and naive.sketch is None
    opt = SO.optimize_schedule(naive)
    assert opt.provenance == "konig"
    model = PL.synthetic_torus((4, 4))
    packed = SO.congestion_aware_repack(opt, model, None, record=False)
    if packed is not opt:
        assert packed.provenance == "congestion"
    out = SY.synthesize_schedule(opt, model)
    assert out is not None
    assert out.provenance == f"synthesized:{out.sketch}"
    assert out.sketch in SY.SKETCHES
    assert out.modeled_cost is not None
    assert out.modeled_cost.serial_link_time == \
        PL.schedule_cost(model, out).serial_link_time
    # schedule_provenance covers dynamic + pre-artifact types.
    dyn = S.compile_dynamic(topo.one_peer_exp2_phases(8), 8)
    assert S.schedule_provenance(dyn) == "naive"
    assert dyn.provenance == "naive"


def test_as_compiled_inherits_unspecified_fields():
    w = topo.weight_matrix(topo.RingGraph(8))
    sched = S._build_schedule(w, optimize=False)
    a = S.as_compiled(sched, provenance="konig", sketch="hierarchical")
    b = S.as_compiled(a, lowering="window")
    assert (b.provenance, b.sketch, b.lowering) == \
        ("konig", "hierarchical", "window")
    assert b.rounds is a.rounds and b.n == a.n


def test_window_plan_lowering_matches_rounds():
    w = topo.weight_matrix(topo.RandomRegularGraph(12, 4, seed=3))
    sched = S._build_schedule(w, optimize=True)
    plan = sched.window_plan()
    assert len(plan) == 12
    flat = {(s, d): wt for s, targets in enumerate(plan)
            for d, wt in targets}
    expect = {}
    for rnd in sched.rounds:
        for s, d in rnd.pairs:
            expect[(s, d)] = float(rnd.send_scale[s])
    assert flat == expect


def test_compile_cache_info_carries_provenance():
    SO.clear_compile_cache()
    S.compile_static(topo.RandomRegularGraph(16, 4, seed=0))
    S.compile_static(topo.RingGraph(8))
    info = SO.compile_cache_info()
    assert info["entries"] == 2
    assert info["by_provenance"].get("konig") == 1  # the random-regular
    assert info["by_provenance"].get("naive") == 1  # ring: already minimal


# ---------------------------------------------------------------------------
# Synthesis properties
# ---------------------------------------------------------------------------

def _random_digraph_matrix(rng) -> np.ndarray:
    n = 32  # must match the model's node count
    w = (rng.random((n, n)) < rng.uniform(0.08, 0.3)) * rng.random((n, n))
    np.fill_diagonal(w, rng.random(n))
    return w


def test_property_synthesized_schedules_exact_equivalent_and_budgeted():
    """Random digraphs + the named families: synthesis preserves the
    effective weight matrix BIT-identically, emits valid rounds, and
    stays within the round budget."""
    rng = np.random.default_rng(7)
    model = PL.synthetic_torus((4, 8))
    mats = [_random_digraph_matrix(rng) for _ in range(12)]
    mats += [topo.weight_matrix(topo.ExponentialTwoGraph(32)),
             topo.weight_matrix(topo.StarGraph(32)),
             topo.weight_matrix(topo.RandomRegularGraph(32, 4, seed=1))]
    for i, w in enumerate(mats):
        sched = S._build_schedule(w, optimize=True)
        for budget in (2.0, 1.0):
            out = SY.synthesize_schedule(sched, model,
                                         budget_factor=budget)
            if out is None:
                continue  # sketch infeasible under a tight budget: fine
            assert_valid_rounds(out)
            np.testing.assert_array_equal(
                effective_matrix(sched), effective_matrix(out),
                err_msg=f"graph {i}: synthesis changed the weights")
            cap = max(len(sched.rounds),
                      math.ceil(budget * SO.min_rounds(sched)))
            assert len(out.rounds) <= cap, \
                f"graph {i}: {len(out.rounds)} rounds > budget {cap}"


def test_synthesis_deterministic_within_process():
    model = PL.synthetic_torus((8, 8))
    w = topo.weight_matrix(topo.RandomRegularGraph(64, 4, seed=0))
    sched = S._build_schedule(w, optimize=True)
    out1 = SY.synthesize_schedule(sched, model)
    SY.clear_synth_cache()  # force a real recomputation, not a memo hit
    out2 = SY.synthesize_schedule(sched, model)
    assert out1 is not out2
    assert out1.sketch == out2.sketch
    assert len(out1.rounds) == len(out2.rounds)
    for r1, r2 in zip(out1.rounds, out2.rounds):
        assert r1.pairs == r2.pairs
        np.testing.assert_array_equal(r1.send_scale, r2.send_scale)


_SUBPROCESS_DIGEST = r"""
import hashlib
import numpy as np
from bluefog_tpu import topology as topo
from bluefog_tpu.ops import placement as PL, schedule as S, synthesis as SY
model = PL.synthetic_torus((4, 8), n_slices=2)
w = topo.weight_matrix(topo.RandomRegularGraph(64, 4, seed=5))
sched = S._build_schedule(w, optimize=True)
out = SY.synthesize_schedule(sched, model)
h = hashlib.sha256()
h.update(out.provenance.encode())
for rnd in out.rounds:
    h.update(repr(rnd.pairs).encode())
    h.update(rnd.send_scale.tobytes())
print(h.hexdigest())
"""


def test_synthesis_deterministic_across_processes():
    """Identical inputs → identical artifact on every rank: a fresh
    interpreter (standing in for another SPMD process) must synthesize a
    bit-identical schedule."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    local = subprocess.run(
        [sys.executable, "-c", _SUBPROCESS_DIGEST],
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert local.returncode == 0, local.stderr
    import hashlib
    model = PL.synthetic_torus((4, 8), n_slices=2)
    w = topo.weight_matrix(topo.RandomRegularGraph(64, 4, seed=5))
    sched = S._build_schedule(w, optimize=True)
    SY.clear_synth_cache()
    out = SY.synthesize_schedule(sched, model)
    h = hashlib.sha256()
    h.update(out.provenance.encode())
    for rnd in out.rounds:
        h.update(repr(rnd.pairs).encode())
        h.update(rnd.send_scale.tobytes())
    assert local.stdout.strip() == h.hexdigest()


# ---------------------------------------------------------------------------
# Acceptance: beat congestion_aware_repack on serial_link_time
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dims,slices,family,strict", [
    ((8, 8), 1, "exp2", True),
    ((8, 8), 1, "rr4", True),
    ((4, 4), 4, "rr4", True),
    ((4, 8), 2, "exp2", False),  # provably at the lower bound: tie
    ((4, 8), 2, "rr4", False),
], ids=["exp2@8x8", "rr4@8x8", "rr4@4x(4x4)", "exp2@2slice", "rr4@2slice"])
def test_acceptance_beats_congestion_repack(dims, slices, family, strict):
    model = PL.synthetic_torus(dims, n_slices=slices)
    n = len(model.device_node)
    g = topo.ExponentialTwoGraph(n) if family == "exp2" \
        else topo.RandomRegularGraph(n, 4, seed=0)
    sched = S._build_schedule(topo.weight_matrix(g), optimize=True)
    packed = SO.congestion_aware_repack(sched, model, None,
                                        budget_factor=2.0, record=False)
    chosen, ratio = SY.select_schedule(sched, packed, model, None)
    ps = PL.schedule_cost(model, packed).serial_link_time
    cs = PL.schedule_cost(model, chosen).serial_link_time
    assert cs <= ps + 1e-9, "selection must never be worse than packed"
    np.testing.assert_array_equal(effective_matrix(sched),
                                  effective_matrix(chosen))
    assert_valid_rounds(chosen)
    if strict:
        assert cs < ps - 1e-9, \
            f"expected a strict serial win ({cs} vs packed {ps})"
        assert ratio > 1.0
        assert S.schedule_provenance(chosen).startswith("synthesized")
    else:
        # A tie is only acceptable at provable optimality.
        assert ps <= lower_bound(model, sched) + 1e-9
        assert chosen is packed  # packed retained on ties


def test_select_schedule_retains_packed_on_tie_and_records():
    """Ring on its matching torus is already optimal: the selection must
    hand back the PACKED object itself (ratio 1.0), and with record=True
    publish the gauge + provenance info series."""
    model = PL.synthetic_torus((8,))
    sched = S._build_schedule(topo.weight_matrix(topo.RingGraph(8)),
                              optimize=True)
    packed = SO.congestion_aware_repack(sched, model, None, record=False)
    telemetry.reset()
    chosen, ratio = SY.select_schedule(sched, packed, model, None,
                                       record=True)
    assert chosen is packed and ratio == 1.0
    snap = telemetry.snapshot()
    assert snap.get("bf_schedule_synth_improvement_ratio") == 1.0
    prov = S.schedule_provenance(packed)
    assert snap.get(
        'bf_schedule_provenance{provenance="%s"}' % prov) == 1.0
    telemetry.reset()


def test_synthesis_noop_paths():
    sched = S._build_schedule(topo.weight_matrix(topo.RingGraph(8)),
                              optimize=True)
    model = PL.synthetic_torus((2, 4))
    assert SY.synthesize_schedule(sched, None) is None
    assert SY.synthesize_schedule(sched, model, budget_factor=0.0) is None
    # Rank-count mismatch (machine-level schedules): bow out.
    small = S._build_schedule(topo.weight_matrix(topo.RingGraph(4)),
                              optimize=True)
    assert SY.synthesize_schedule(small, model) is None


def test_synth_cache_memoizes_and_reports():
    SY.clear_synth_cache()
    model = PL.synthetic_torus((4, 8))
    sched = S._build_schedule(
        topo.weight_matrix(topo.RandomRegularGraph(32, 4, seed=2)),
        optimize=True)
    out1 = SY.synthesize_schedule(sched, model)
    out2 = SY.synthesize_schedule(sched, model)
    assert out1 is out2  # memo hit, same artifact object
    info = SY.synth_cache_info()
    assert info["entries"] >= 1
    assert any(k.startswith("synthesized") or k == "none"
               for k in info["by_provenance"])


# ---------------------------------------------------------------------------
# Wire stats + dispatch provenance
# ---------------------------------------------------------------------------

def test_wire_stats_fourth_element_provenance():
    model = PL.synthetic_torus((8, 8))
    sched = S._build_schedule(
        topo.weight_matrix(topo.RandomRegularGraph(64, 4, seed=0)),
        optimize=True)
    out = SY.synthesize_schedule(sched, model)
    stats = C.schedule_wire_stats(out)
    assert len(stats) == 4
    assert stats[3] == out.provenance
    assert stats[1] == 64 * 4  # edges invariant under synthesis


# ---------------------------------------------------------------------------
# End-to-end wiring through bf.init / set_topology
# ---------------------------------------------------------------------------

def _run_op(topo_fn, x):
    bf.init(topo_fn)
    out = np.asarray(bf.neighbor_allreduce(x))
    info = bf.synthesis_info()
    keys = list(basics._ctx._static_scheds)
    bf.shutdown()
    return out, info, keys


def test_env_hatch_restores_pr5_path_and_output_equivalence(devices):
    topo_fn = lambda: topo.RandomRegularGraph(N, 4, seed=1)
    x = np.random.default_rng(0).standard_normal((N, 16)).astype(np.float32)

    _env(BLUEFOG_TPU_SCHEDULE_SYNTH="0", BLUEFOG_TPU_FAKE_TORUS="2x4")
    out_off, info_off, _ = _run_op(topo_fn, x)
    assert info_off is None  # PR-5 path: no synthesis anywhere

    _env(BLUEFOG_TPU_SCHEDULE_SYNTH="1", BLUEFOG_TPU_FAKE_TORUS="2x4")
    out_on, info_on, _ = _run_op(topo_fn, x)
    assert info_on is not None
    assert info_on["improvement_ratio"] >= 1.0
    assert info_on["sketch"] == "auto"
    # Round regrouping shifts fp summation order only.
    assert float(np.abs(out_off - out_on).max()) <= 1e-6


def test_schedule_cache_keys_carry_synth_path_tag(devices):
    """The bugfix satellite: a BLUEFOG_TPU_SCHEDULE_SYNTH toggle
    mid-process must MISS the context schedule cache (the key carries the
    path tag), never serve a schedule compiled under the other path."""
    _env(BLUEFOG_TPU_SCHEDULE_SYNTH="1", BLUEFOG_TPU_FAKE_TORUS="2x4")
    bf.init(lambda: topo.RandomRegularGraph(N, 4, seed=1))
    try:
        x = np.ones((N, 4), np.float32)
        bf.neighbor_allreduce(x)
        keys_on = set(basics._ctx._static_scheds)
        assert all(k[-2] == (True, "auto", 2.0)
                   for k in keys_on if k[0] == "static")
        # Toggle mid-process WITHOUT set_topology: the next dispatch must
        # compile fresh under the new tag, not reuse the synthesis-path
        # entry.
        os.environ["BLUEFOG_TPU_SCHEDULE_SYNTH"] = "0"
        config.reload()
        bf.neighbor_allreduce(x)
        keys_both = set(basics._ctx._static_scheds)
        static_tags = {k[-2] for k in keys_both if k[0] == "static"}
        assert static_tags == {(True, "auto", 2.0), (False, "auto", 2.0)}
    finally:
        bf.shutdown()


def test_dispatch_records_synth_gauges_and_provenance_counter(devices):
    _env(BLUEFOG_TPU_SCHEDULE_SYNTH="1", BLUEFOG_TPU_FAKE_TORUS="2x4")
    telemetry.reset()
    bf.init(lambda: topo.RandomRegularGraph(N, 4, seed=0))
    try:
        x = np.arange(N * 4, dtype=np.float32).reshape(N, 4)
        bf.neighbor_allreduce(x)
        snap = telemetry.snapshot()
        assert snap.get("bf_schedule_synth_improvement_ratio", 0) >= 1.0
        provs = [k for k in snap if k.startswith("bf_schedule_provenance{")]
        assert len(provs) == 1  # exactly one info series
        calls = [k for k in snap
                 if k.startswith("bf_comm_schedule_provenance_total")]
        assert calls and all('op="neighbor_allreduce"' in k for k in calls)
    finally:
        bf.shutdown()
        telemetry.reset()


def test_synth_gauges_cleared_when_disabled(devices):
    _env(BLUEFOG_TPU_SCHEDULE_SYNTH="1", BLUEFOG_TPU_FAKE_TORUS="2x4")
    telemetry.reset()
    bf.init(lambda: topo.RandomRegularGraph(N, 4, seed=0))
    assert "bf_schedule_synth_improvement_ratio" in telemetry.snapshot()
    bf.shutdown()
    _env(BLUEFOG_TPU_SCHEDULE_SYNTH="0", BLUEFOG_TPU_FAKE_TORUS="2x4")
    bf.init(lambda: topo.RandomRegularGraph(N, 4, seed=0))
    snap = telemetry.snapshot()
    assert "bf_schedule_synth_improvement_ratio" not in snap
    assert not [k for k in snap if k.startswith("bf_schedule_provenance{")]
    bf.shutdown()


def test_sketch_knob_validated():
    os.environ["BLUEFOG_TPU_SCHEDULE_SYNTH_SKETCH"] = "typo-sketch"
    try:
        with pytest.raises(ValueError, match="not a known sketch"):
            config.reload()
    finally:
        os.environ.pop("BLUEFOG_TPU_SCHEDULE_SYNTH_SKETCH", None)
        config.reload()  # restore a valid cached config immediately


# ---------------------------------------------------------------------------
# schedule-dump CLI
# ---------------------------------------------------------------------------

def test_schedule_dump_report():
    from bluefog_tpu import tools
    text = tools.schedule_dump("exp2", 64, "8x8")
    assert "naive" in text and "konig" in text and "congestion" in text
    assert "synthesized:" in text
    assert "serial_link_time" in text
    text2 = tools.schedule_dump("random-regular", 64, "4x4", slices=4,
                                show_rounds=True)
    assert "4 slice(s)" in text2 and "round " in text2
    with pytest.raises(SystemExit):
        tools.schedule_dump("exp2", 63, "8x8")  # node-count mismatch
    with pytest.raises(SystemExit):
        tools.schedule_dump("nope", 64, "8x8")
