"""Elastic run-loop tests: crash/resume bit-exactness, SIGTERM preemption,
checkpoint pruning, multi-process agreed resume (SURVEY §5.3 — the
reference claims fault tolerance but implements only shutdown)."""

import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import bluefog_tpu as bf
from bluefog_tpu.utils import checkpoint
from bluefog_tpu.utils.elastic import Preempted, run_elastic


@pytest.fixture(autouse=True)
def _init():
    if not bf.initialized():
        bf.init()
    yield


def _make_step():
    """Deterministic decentralized step: neighbor-average + step-keyed
    noise (a real collective, so resume exactness covers the comm path)."""
    n = bf.size()
    bf.set_topology(bf.topology_util.RingGraph(n))

    def step_fn(state, step):
        x = bf.neighbor_allreduce(state["x"])
        key = jax.random.PRNGKey(step)
        return {"x": x + 0.01 * jax.random.normal(key, x.shape),
                "count": state["count"] + 1}

    x0 = np.random.RandomState(0).randn(n, 4).astype(np.float32)
    return step_fn, {"x": jnp.asarray(x0),
                     "count": jnp.zeros((), jnp.int32)}


def test_uninterrupted_vs_crash_resume_bit_exact(tmp_path):
    step_fn, state0 = _make_step()
    straight = state0
    for s in range(10):
        straight = step_fn(straight, s)

    # crash: run 6 steps with saves every 4, "die" (no final save) ...
    crash_dir = str(tmp_path / "ck")
    partial = state0
    for s in range(6):
        partial = step_fn(partial, s)
        if (s + 1) % 4 == 0:
            checkpoint.save(crash_dir, partial, step=s + 1)
    # ... then run_elastic resumes from step 4 and replays to 10
    resumed = run_elastic(step_fn, state0, ckpt_dir=crash_dir, num_steps=10,
                          save_every=4)
    np.testing.assert_array_equal(np.asarray(straight["x"]),
                                  np.asarray(resumed["x"]))
    assert int(resumed["count"]) == 10
    assert checkpoint.latest_step(crash_dir) == 10


def test_fresh_run_saves_and_final_state(tmp_path):
    step_fn, state0 = _make_step()
    out = run_elastic(step_fn, state0, ckpt_dir=str(tmp_path / "a"),
                      num_steps=5, save_every=2)
    assert int(out["count"]) == 5
    assert checkpoint.latest_step(str(tmp_path / "a")) == 5


def test_pruning_keeps_newest(tmp_path):
    step_fn, state0 = _make_step()
    d = str(tmp_path / "p")
    run_elastic(step_fn, state0, ckpt_dir=d, num_steps=10, save_every=1,
                keep=3)
    steps = sorted(int(x.split("_")[1]) for x in os.listdir(d)
                   if x.startswith("step_"))
    assert steps == [8, 9, 10]


def test_resume_past_end_returns_restored(tmp_path):
    step_fn, state0 = _make_step()
    d = str(tmp_path / "done")
    final = run_elastic(step_fn, state0, ckpt_dir=d, num_steps=3,
                        save_every=10)
    again = run_elastic(step_fn, state0, ckpt_dir=d, num_steps=3)
    np.testing.assert_array_equal(np.asarray(final["x"]),
                                  np.asarray(again["x"]))


def test_sigterm_saves_and_raises_preempted(tmp_path):
    step_fn, state0 = _make_step()
    d = str(tmp_path / "pre")

    def poke(_state, step):
        if step == 2:  # preemption notice arrives mid-run
            os.kill(os.getpid(), signal.SIGTERM)

    with pytest.raises(Preempted) as ei:
        run_elastic(step_fn, state0, ckpt_dir=d, num_steps=100,
                    save_every=50, on_step=poke)
    assert ei.value.step == 3
    assert checkpoint.latest_step(d) == 3
    # default SIGTERM disposition restored
    assert signal.getsignal(signal.SIGTERM) in (signal.SIG_DFL,
                                                signal.Handlers.SIG_DFL)
    # resume completes the run from the preemption point
    out = run_elastic(step_fn, state0, ckpt_dir=d, num_steps=5, save_every=50)
    assert int(out["count"]) == 5


def test_sync_save_mode_matches_async(tmp_path):
    step_fn, state0 = _make_step()
    a = run_elastic(step_fn, state0, ckpt_dir=str(tmp_path / "a"),
                    num_steps=6, save_every=2, async_save=True)
    b = run_elastic(step_fn, state0, ckpt_dir=str(tmp_path / "b"),
                    num_steps=6, save_every=2, async_save=False)
    np.testing.assert_array_equal(np.asarray(a["x"]), np.asarray(b["x"]))
    assert checkpoint.latest_step(str(tmp_path / "a")) == 6
    assert checkpoint.latest_step(str(tmp_path / "b")) == 6


def test_async_save_errors_surface_on_main_thread(tmp_path, monkeypatch):
    """A failing background write must fail the run, not vanish into the
    worker thread."""
    from bluefog_tpu.utils import elastic
    step_fn, state0 = _make_step()
    calls = {"n": 0}
    real_save = checkpoint.save

    def flaky(path, tree, **kw):
        calls["n"] += 1
        if calls["n"] == 2:
            raise OSError("disk full")
        return real_save(path, tree, **kw)

    monkeypatch.setattr(elastic.checkpoint, "save", flaky)
    with pytest.raises(OSError, match="disk full"):
        run_elastic(step_fn, state0, ckpt_dir=str(tmp_path / "f"),
                    num_steps=10, save_every=2)


def test_background_write_error_does_not_mask_step_error(tmp_path,
                                                         monkeypatch):
    """A pending background-write failure must not replace a real step_fn
    exception during unwinding."""
    from bluefog_tpu.utils import elastic
    step_fn, state0 = _make_step()
    real_save = checkpoint.save
    calls = {"n": 0}

    def flaky(path, tree, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("disk full")
        return real_save(path, tree, **kw)

    monkeypatch.setattr(elastic.checkpoint, "save", flaky)

    def poke(_s, step):
        if step == 3:  # after the step-2 save was submitted (and failed)
            raise RuntimeError("model blew up")

    with pytest.raises(RuntimeError, match="model blew up"):
        run_elastic(step_fn, state0, ckpt_dir=str(tmp_path / "m"),
                    num_steps=10, save_every=2, on_step=poke)


def test_sigterm_during_final_step_completes_normally(tmp_path):
    """A preemption notice landing on the last step must not turn a finished
    run into a Preempted restart."""
    step_fn, state0 = _make_step()
    d = str(tmp_path / "fin")

    def poke(_state, step):
        if step == 4:  # the final step (num_steps=5)
            os.kill(os.getpid(), signal.SIGTERM)

    out = run_elastic(step_fn, state0, ckpt_dir=d, num_steps=5,
                      save_every=50, on_step=poke)
    assert int(out["count"]) == 5
    assert checkpoint.latest_step(d) == 5


def test_max_common_step_survives_pruned_frontiers():
    """Agreement is the newest COMMON step: min(latest) would name step 3,
    which the fast process already pruned."""
    from bluefog_tpu.utils.elastic import _max_common_step
    fast = [9, 12, 15]      # pruned everything below 9
    slow = [3, 6, 9]        # died mid-save of 12
    assert _max_common_step([fast, slow]) == 9
    assert _max_common_step([[0, 0, 0], [3]]) == 0     # fresh process
    assert _max_common_step([[5], [7]]) == 0           # nothing in common


def test_restart_below_frontier_discards_stale_checkpoints(monkeypatch,
                                                           tmp_path):
    """A veteran forced to restart at step 0 (replacement peer had nothing
    in common AND storage is not shared, so the cross-geometry frontier
    degrades to 0 too) must drop its stale newer dirs, or pruning would
    delete every new save and the job would never checkpoint durably
    again."""
    from bluefog_tpu.utils import elastic
    step_fn, state0 = _make_step()
    d = str(tmp_path / "vet")
    for s in (98, 99, 100):  # veteran frontier from a previous life
        checkpoint.save(d, state0, step=s)
    monkeypatch.setattr(elastic, "_agreed_start", lambda *a: 0)
    monkeypatch.setattr(elastic, "_foreign_frontier", lambda *a: 0)
    out = run_elastic(step_fn, state0, ckpt_dir=d, num_steps=5,
                      save_every=2, keep=2)
    assert int(out["count"]) == 5
    assert checkpoint.list_steps(d) == [4, 5]  # stale 98-100 gone, run saved


def test_multiprocess_requires_per_process(monkeypatch, tmp_path):
    step_fn, state0 = _make_step()
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    with pytest.raises(ValueError, match="per_process=True"):
        run_elastic(step_fn, state0, ckpt_dir=str(tmp_path / "x"),
                    num_steps=1)


_MULTIPROC_SCRIPT = r"""
import os, sys
sys.path.insert(0, "@REPO@")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import bluefog_tpu as bf
from bluefog_tpu.utils.elastic import run_elastic

bf.init_distributed()
n = bf.size()

def step_fn(state, step):
    return {"x": state["x"] * 1.5 + step}

state0 = {"x": jnp.ones((4,), jnp.float32)}
crash_at = int(os.environ.get("CRASH_AT", "0"))

def poke(_s, step):
    if crash_at and step + 1 == crash_at:
        os._exit(17)  # hard crash: no final save

out = run_elastic(step_fn, state0, ckpt_dir=os.environ["CKDIR"],
                  num_steps=8, save_every=3, per_process=True, on_step=poke)
expect = jnp.ones((4,), jnp.float32)
for s in range(8):
    expect = expect * 1.5 + s
np.testing.assert_allclose(np.asarray(out["x"]), np.asarray(expect))
print("ELASTIC-OK", jax.process_index())
"""


_GANG_SCRIPT = r"""
import os, sys
sys.path.insert(0, "@REPO@")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import bluefog_tpu as bf
from bluefog_tpu.utils.elastic import run_elastic

bf.init_distributed()

def step_fn(state, step):
    return {"x": state["x"] * 2.0 + step}

marker = os.environ["MARKER"]
crashes = int(os.environ.get("CRASHES", "1"))
# Captured ONCE at startup: re-reading inside poke would let the surviving
# rank observe the crasher's fresh marker mid-incarnation and self-crash
# in the same incarnation, collapsing two planned crashes into one.
inc = len(open(marker).read()) if os.path.exists(marker) else 0

def poke(_s, step):
    # The first `crashes` incarnations die hard (alternating which rank) a
    # couple of steps past a save boundary; survivors must be reaped.
    if inc < crashes and step + 1 == 5 + inc \
            and jax.process_index() == inc % 2:
        with open(marker, "a") as f:
            f.write("x")
        os._exit(1)

out = run_elastic(step_fn, {"x": jnp.ones((2,), jnp.float32)},
                  ckpt_dir=os.environ["CKDIR"], num_steps=9, save_every=3,
                  per_process=True, on_step=poke)
expect = jnp.ones((2,), jnp.float32)
for s in range(9):
    expect = expect * 2.0 + s
np.testing.assert_allclose(np.asarray(out["x"]), np.asarray(expect))
print("GANG-OK", jax.process_index())
"""


@pytest.mark.slow
@pytest.mark.parametrize("crashes", [1, 2])
def test_bfrun_gang_restart_completes_job(tmp_path, crashes):
    """Full-stack fault tolerance: ranks crash (in successive incarnations,
    alternating which rank dies), bfrun --restarts reaps the survivors,
    relaunches the gang, and run_elastic resumes to the exact uninterrupted
    result."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "gang.py"
    script.write_text(_GANG_SCRIPT.replace("@REPO@", repo))
    env = dict(os.environ, CKDIR=str(tmp_path / "ck"),
               MARKER=str(tmp_path / "crash-count"),
               CRASHES=str(crashes))
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.run", "-np", "2",
         "--devices-per-proc", "2", "--restarts", str(crashes),
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=600, cwd=repo, env=env)
    assert out.returncode == 0, (
        f"stdout={out.stdout}\nstderr={out.stderr}")
    assert "restarting the gang" in out.stderr
    assert f"(attempt {crashes}/{crashes})" in out.stderr
    assert out.stdout.count("GANG-OK") == 2, out.stdout


@pytest.mark.slow
def test_multiprocess_crash_and_resume(tmp_path):
    """Two processes crash hard at step 5 (after the step-3 saves), restart,
    agree on the resume step, and finish with the exact uninterrupted
    result."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "elastic_mp.py"
    script.write_text(_MULTIPROC_SCRIPT.replace("@REPO@", repo))
    env = dict(os.environ, CKDIR=str(tmp_path / "ck"))

    run = [sys.executable, "-m", "bluefog_tpu.run", "-np", "2",
           "--devices-per-proc", "2", sys.executable, str(script)]
    first = subprocess.run(run, capture_output=True, text=True, timeout=600,
                           cwd=repo, env=dict(env, CRASH_AT="5"))
    assert "ELASTIC-OK" not in first.stdout  # both died before finishing
    second = subprocess.run(run, capture_output=True, text=True, timeout=600,
                            cwd=repo, env=env)
    assert second.returncode == 0, (
        f"stdout={second.stdout}\nstderr={second.stderr}")
    assert second.stdout.count("ELASTIC-OK") == 2, second.stdout


def test_world_size_reshard_unit(tmp_path):
    """Resume at a different world size (single-process harness): four old
    per-process dirs hold n=8 rank-major state with DISTINCT authoritative
    rows (others stale); the new n=4 run stitches the authoritative rows,
    consensus-averages them, and resumes from the old frontier.  The state
    includes a NamedTuple with NON-alphabetical same-shape fields — the
    reshard must pair leaves by key path, not flat order."""
    import collections
    St = collections.namedtuple("St", ["zz", "aa"])  # sorts to aa, zz
    base = str(tmp_path / "ws")
    n_old, P_old, D = 8, 4, 3
    true = np.arange(n_old * D, dtype=np.float32).reshape(n_old, D)
    for k in range(P_old):
        copy = np.full((n_old, D), -1000.0, np.float32)  # stale poison
        rows = np.array_split(np.arange(n_old), P_old)[k]
        copy[rows] = true[rows]  # only owned rows authoritative
        checkpoint.save(
            os.path.join(base, f"proc{k}"),
            {"w": copy, "count": np.int32(6),
             "nt": St(zz=np.float32(11.0), aa=np.float32(22.0))}, step=6)

    seen = {}

    def on_restore(state, start):
        seen["start"] = start
        seen["w"] = np.asarray(state["w"]).copy()
        seen["nt"] = state["nt"]

    def step_fn(state, step):
        return {"w": state["w"] + 1.0, "count": state["count"],
                "nt": state["nt"]}

    state0 = {"w": jnp.zeros((4, D), jnp.float32), "count": np.int32(0),
              "nt": St(zz=np.float32(0.0), aa=np.float32(0.0))}
    out = run_elastic(step_fn, state0, ckpt_dir=base, num_steps=8,
                      save_every=100, on_restore=on_restore)
    assert seen["start"] == 6
    # Every new row is the consensus average of the 8 AUTHORITATIVE rows —
    # the stale poison rows must not leak into the average.
    np.testing.assert_allclose(seen["w"],
                               np.broadcast_to(true.mean(0), (4, D)),
                               rtol=1e-6)
    # NamedTuple fields restored by NAME, not by sorted-key flat order.
    assert float(seen["nt"].zz) == 11.0 and float(seen["nt"].aa) == 22.0
    assert int(out["count"]) == 6  # non-rank-major leaf passes through
    np.testing.assert_allclose(np.asarray(out["w"]),
                               seen["w"] + 2.0, rtol=1e-6)  # steps 7, 8


def test_world_size_reshard_nonuniform_ownership(tmp_path):
    """Non-uniform placements (bfrun --hosts h1:3,h2:1 style): row
    ownership is NOT an even split, so the stitch must follow the
    persisted owned_ranks.json — an even array_split would take stale
    rows from the wrong process.  Also pins integer-leaf consensus:
    per-rank int counters are rounded to nearest, not truncated."""
    import json
    from bluefog_tpu.utils import elastic as EL
    base = str(tmp_path / "wsnu")
    n_old, D = 4, 3
    owned_of = [[0, 1, 2], [3]]  # 2 old procs, 3:1 split
    true = np.arange(n_old * D, dtype=np.float32).reshape(n_old, D)
    # Integer rank-major leaf whose authoritative values average to x.5:
    # truncation would bias down, rint rounds half to even (2).
    ctr = np.array([1, 2, 1, 2], np.int32)
    for k, owned in enumerate(owned_of):
        copy = np.full((n_old, D), -1000.0, np.float32)
        copy[owned] = true[owned]
        c = np.full((n_old,), 50, np.int32)  # poison
        c[owned] = ctr[owned]
        d = os.path.join(base, f"proc{k}")
        checkpoint.save(d, {"w": copy, "c": c}, step=6)
        with open(os.path.join(d, EL._OWNED_FILE), "w") as fh:
            json.dump(owned, fh)

    seen = {}

    def on_restore(state, start):
        seen["w"] = np.asarray(state["w"]).copy()
        seen["c"] = np.asarray(state["c"]).copy()

    state0 = {"w": jnp.zeros((2, D), jnp.float32),
              "c": np.zeros((2,), np.int32)}
    run_elastic(lambda s, t: s, state0, ckpt_dir=base, num_steps=7,
                save_every=100, on_restore=on_restore)
    np.testing.assert_allclose(seen["w"],
                               np.broadcast_to(true.mean(0), (2, D)),
                               rtol=1e-6)
    # mean([1,2,1,2]) = 1.5 -> rint -> 2 (not int-truncated 1)
    np.testing.assert_array_equal(seen["c"], np.full((2,), 2, np.int32))


def test_world_size_reshard_survives_crash_before_first_save(tmp_path):
    """After a world-size resume, a crash BEFORE the first new-geometry
    save leaves only old-shape checkpoints at the frontier; the next
    restart must reshard again (same frontier, differing geometry), not
    wedge on a shape-mismatched restore."""
    base = str(tmp_path / "ws2")
    n_old, P_old, D = 8, 2, 3
    true = np.arange(n_old * D, dtype=np.float32).reshape(n_old, D)
    for k in range(P_old):
        copy = np.full((n_old, D), -7.0, np.float32)
        rows = np.array_split(np.arange(n_old), P_old)[k]
        copy[rows] = true[rows]
        checkpoint.save(os.path.join(base, f"proc{k}"), {"w": copy}, step=6)

    def step_fn(state, step):
        return {"w": state["w"] + 1.0}

    state0 = {"w": jnp.zeros((4, D), jnp.float32)}
    # First incarnation "crashes before saving": num_steps == frontier, so
    # run_elastic restores (resharded) and returns without writing.
    first = run_elastic(step_fn, state0, ckpt_dir=base, num_steps=6,
                        save_every=100)
    expect = np.broadcast_to(true.mean(0), (4, D))
    np.testing.assert_allclose(np.asarray(first["w"]), expect, rtol=1e-6)
    # Second incarnation: old dirs still hold the only frontier (old
    # shapes); it must reshard again and complete.
    second = run_elastic(step_fn, state0, ckpt_dir=base, num_steps=8,
                         save_every=100)
    np.testing.assert_allclose(np.asarray(second["w"]), expect + 2.0,
                               rtol=1e-6)


_WORLD_SIZE_SCRIPT = r"""
import os, sys
sys.path.insert(0, "@REPO@")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import optax
import bluefog_tpu as bf
from bluefog_tpu.utils.elastic import run_elastic

bf.init_distributed()
n = bf.size()
DIM, SAMPLES = 4, 16
rng = np.random.RandomState(0)
w_star = rng.randn(DIM, 1)
A_all = rng.randn(8, SAMPLES, DIM)          # 8 shards, defined for n=8
y_all = A_all @ w_star + 0.01 * rng.randn(8, SAMPLES, 1)
A = jnp.asarray(A_all[:n])                   # this world size's shards
y = jnp.asarray(y_all[:n])

def compute_grads(params):
    def loss(w_leaf, A_r, y_r):
        return jnp.mean((A_r @ w_leaf - y_r) ** 2)
    return {"w": jax.vmap(jax.grad(loss))(params["w"], A, y)}
compute_grads = jax.jit(compute_grads)

opt = bf.optim.DistributedNeighborAllreduceOptimizer(optax.sgd(0.05))
params0 = {"w": jnp.asarray(
    np.random.RandomState(1).randn(n, DIM, 1).astype(np.float32) * 2.0)}
state0 = {"p": params0, "o": opt.init(params0)}

def step_fn(state, step):
    p, o = opt.step(state["p"], compute_grads(state["p"]), state["o"])
    return {"p": p, "o": o}

resumed_at = []
def on_restore(state, start):
    resumed_at.append(start)

NUM = int(os.environ["NUM_STEPS"])
# The collective optimizer's state is globally sharded over the mesh, so
# the coordinated (shared-dir) checkpoint layout applies.
out = run_elastic(step_fn, state0, ckpt_dir=os.environ["CKDIR"],
                  num_steps=NUM, save_every=20, per_process=False,
                  on_restore=on_restore)
if os.environ.get("EXPECT_RESUME"):
    assert resumed_at == [int(os.environ["EXPECT_RESUME"])], resumed_at
w = bf.to_numpy(out["p"]["w"])
pred = np.einsum('msd,ndo->mnso', np.asarray(A), w)
mse = float(np.mean((pred - np.asarray(y)[:, None]) ** 2))
assert mse < 0.05, f"world-size elastic MSE {mse}"
print("WS-ELASTIC-OK", jax.process_index(), "mse", round(mse, 4), flush=True)
"""


@pytest.mark.slow
def test_world_size_elastic_resume_under_bfrun(tmp_path):
    """True elasticity (neither framework had it): train decentralized at
    n=8 over 4 processes, stop, resume the SAME ckpt_dir at n=4 over 2
    processes — the new gang stitches the old authoritative rows,
    consensus-averages across the shrunk rank axis, resumes at the old
    frontier, and converges."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "ws.py"
    script.write_text(_WORLD_SIZE_SCRIPT.replace("@REPO@", repo))
    env = dict(os.environ, CKDIR=str(tmp_path / "ck"))

    def run(np_procs, steps, expect_resume=""):
        e = dict(env, NUM_STEPS=str(steps))
        if expect_resume:
            e["EXPECT_RESUME"] = expect_resume
        return subprocess.run(
            [sys.executable, "-m", "bluefog_tpu.run", "-np", str(np_procs),
             "--devices-per-proc", "2", sys.executable, str(script)],
            capture_output=True, text=True, timeout=600, cwd=repo, env=e)

    first = run(4, 60)
    assert first.returncode == 0, \
        f"stdout={first.stdout}\nstderr={first.stderr[-4000:]}"
    assert first.stdout.count("WS-ELASTIC-OK") == 4, first.stdout

    second = run(2, 140, expect_resume="60")
    assert second.returncode == 0, \
        f"stdout={second.stdout}\nstderr={second.stderr[-4000:]}"
    assert second.stdout.count("WS-ELASTIC-OK") == 2, second.stdout


def test_invalidate_stale_owned_ranks(tmp_path, caplog):
    """Shrink-resume hygiene: ownership maps in proc dirs beyond the new
    process count are renamed aside (with a warning), so a later stitch's
    partition check cannot silently fall back to even blocks."""
    import json
    import logging

    from bluefog_tpu.utils import elastic
    from bluefog_tpu.utils.logging import get_logger
    base = str(tmp_path)
    for i, rows in enumerate(([0, 1], [2, 3], [4, 5], [6, 7])):
        d = os.path.join(base, f"proc{i}")
        os.makedirs(d)
        with open(os.path.join(d, elastic._OWNED_FILE), "w") as fh:
            json.dump(rows, fh)
    log = get_logger()
    log.addHandler(caplog.handler)
    try:
        with caplog.at_level(logging.WARNING, logger="bluefog_tpu"):
            elastic._invalidate_stale_owned_ranks(base, 2)
    finally:
        log.removeHandler(caplog.handler)
    for i in (0, 1):  # surviving dirs keep their maps
        assert os.path.exists(
            os.path.join(base, f"proc{i}", elastic._OWNED_FILE))
    for i in (2, 3):  # stale dirs: renamed aside, not deleted
        assert not os.path.exists(
            os.path.join(base, f"proc{i}", elastic._OWNED_FILE))
        assert os.path.exists(
            os.path.join(base, f"proc{i}", elastic._OWNED_FILE + ".stale"))
    assert any("invalidated the stale owned_ranks.json" in r.message
               for r in caplog.records)


def test_owned_rows_fallback_warns_on_broken_partition(tmp_path, caplog):
    """Maps that no longer partition range(n) must warn before degrading
    to even blocks (the silent wrong-owner attribution ADVICE flagged)."""
    import json
    import logging

    from bluefog_tpu.utils import elastic
    from bluefog_tpu.utils.logging import get_logger
    dirs = []
    for i, rows in enumerate(([0, 1, 2], [2, 3])):  # overlap: not a partition
        d = os.path.join(str(tmp_path), f"proc{i}")
        os.makedirs(d)
        with open(os.path.join(d, elastic._OWNED_FILE), "w") as fh:
            json.dump(rows, fh)
        dirs.append(d)
    log = get_logger()
    log.addHandler(caplog.handler)
    try:
        with caplog.at_level(logging.WARNING, logger="bluefog_tpu"):
            maps = elastic._owned_rows_of(dirs, 4)
    finally:
        log.removeHandler(caplog.handler)
    assert maps == [[0, 1], [2, 3]]  # even-block fallback
    assert any("do not partition" in r.message for r in caplog.records)
