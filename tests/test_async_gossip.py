"""Barrier-free asynchronous window gossip (BLUEFOG_TPU_ASYNC) — the
bounded-staleness fold, stale-residual mass conservation, the fence-free
optimizer step and its exact-collect backstop.

Covers the tentpole's contract surface:
  * knob parsing (`BLUEFOG_TPU_ASYNC_STALENESS_POLICY` validation);
  * fake-clock staleness-policy unit tests across all three commit paths
    (per-message, batched, native-folded entries): exact age-in-steps
    from tagged messages, wall-clock fallback for step-less tags,
    edge-estimate inheritance for unsampled messages;
  * mass conservation under random reject/downweight sequences —
    staging + stale residual == input mass at every point, restored
    EXACTLY into staging by win_fold_stale_residuals;
  * the equivalence oracle: ASYNC=1 with staleness bound infinity and a
    collect cadence matching the legacy fence cadence is BITWISE
    identical to the legacy lockstep path; ASYNC=0 is untouched;
  * churn soundness: the membership controller's step-lag eviction
    threshold widens by the collect-backstop cadence in async mode and
    disables itself without a backstop;
  * telemetry: per-src stale counters, the /healthz "async" block, the
    bf_async_step_lag gauge, churn hygiene (clear_async_staleness), and
    the BLUEFOG_TPU_TELEMETRY=0 zero-mutation guard;
  * checkpoint: the stale-residual store survives a
    win_state_dict/win_load_state_dict round trip.
"""

import threading
import types

import numpy as np
import optax
import pytest

import bluefog_tpu as bf
from bluefog_tpu import topology as topo
from bluefog_tpu.ops import transport as T
from bluefog_tpu.ops import window as W
from bluefog_tpu.utils import config, telemetry


@pytest.fixture
def env(monkeypatch):
    """Set knobs + reload config; restores (and reloads + disarms the
    async mode) afterwards."""
    def set_env(**kv):
        for k, v in kv.items():
            if v is None:
                monkeypatch.delenv(k, raising=False)
            else:
                monkeypatch.setenv(k, str(v))
        config.reload()
    yield set_env
    config.reload()
    W.configure_async()        # ASYNC unset again -> disarmed, state clear
    W.clear_async_staleness()
    T.set_trace_origin_step(-1)
    telemetry.reset()


def _tag(src, seq=1, step=-1, unix_us=None):
    """A synthetic 5-tuple wire trace tag (what trace_strip returns)."""
    import time
    if unix_us is None:
        unix_us = time.time_ns() // 1000
    return (src, seq, 0, unix_us, step)


def _mk_window(name="async_w", n=8, dim=5):
    """A ring window with every rank owned (single process) plus a fake
    multi-process directory, so `_apply_inbound` treats messages as
    transport-applied contributions (the path the policy guards)."""
    bf.init(lambda: topo.RingGraph(n))
    rows = np.zeros((n, dim), np.float32)
    assert bf.win_create(rows, name, zero_init=True)
    saved = W._store.distrib
    W._store.distrib = W._Distrib(
        types.SimpleNamespace(), rank_owner={r: 0 for r in range(n)},
        proc_addr={0: ("127.0.0.1", 1)}, my_proc=0)
    return name, saved


def _teardown(name, saved):
    W._store.distrib = saved
    bf.win_free(name)


# ---------------------------------------------------------------------------
# Knob parsing
# ---------------------------------------------------------------------------

def test_staleness_policy_parse():
    assert config.parse_staleness_policy("reject") == ("reject", 0.0)
    assert config.parse_staleness_policy("downweight:0.25") == \
        ("downweight", 0.25)
    for bad in ("downweight", "downweight:x", "downweight:0",
                "downweight:1.0", "downweight:1.5", "keep", ""):
        with pytest.raises(ValueError):
            config.parse_staleness_policy(bad)


def test_async_knob_defaults(env):
    env(BLUEFOG_TPU_ASYNC=None, BLUEFOG_TPU_ASYNC_STALENESS_STEPS=None,
        BLUEFOG_TPU_ASYNC_STALENESS_POLICY=None,
        BLUEFOG_TPU_ASYNC_COLLECT_EVERY=None)
    cfg = config.get()
    assert not cfg.async_mode
    assert cfg.async_staleness_steps == 0
    assert cfg.async_staleness_policy == "reject"
    assert cfg.async_collect_every == 64
    assert not W.configure_async()
    assert W.async_info() is None


# ---------------------------------------------------------------------------
# Fake-clock staleness policy (all three commit paths)
# ---------------------------------------------------------------------------

def test_policy_reject_per_message(env):
    """A tagged contribution older than the bound is diverted whole into
    the stale-residual store; a fresh one takes the exact legacy path."""
    env(BLUEFOG_TPU_ASYNC="1", BLUEFOG_TPU_ASYNC_STALENESS_STEPS="3")
    name, saved = _mk_window()
    try:
        W.configure_async()
        W.set_async_step(10)
        win = W._store.get(name)
        fresh = np.arange(5, dtype=np.float32) + 1
        stale = np.full(5, 8.0, np.float32)
        # Fresh: origin step 9, age 1 <= 3.
        W._apply_inbound(
            T.OP_ACCUMULATE | T.OP_TRACE_FLAG, name, 1, 0, 1.0, 0.0,
            fresh.tobytes() + T.TRACE_TRAILER.pack(1, 1, 0, 1, 9))
        # Stale: origin step 2, age 8 > 3 -> rejected.
        W._apply_inbound(
            T.OP_ACCUMULATE | T.OP_TRACE_FLAG, name, 1, 0, 1.0, 0.0,
            stale.tobytes() + T.TRACE_TRAILER.pack(1, 2, 0, 1, 2))
        np.testing.assert_array_equal(win.staging[(0, 1)], fresh)
        np.testing.assert_array_equal(win.stale_residual[(0, 1)], stale)
        snap = telemetry.snapshot()
        assert snap.get('bf_win_stale_rejected_total{src="1"}') == 1
        # The freshest-seen peer step fed the lag estimate.
        assert W._async.peer_step[1] == 9
        assert W.async_step_lag() == 9 - 10
    finally:
        _teardown(name, saved)


def test_policy_downweight_and_wallclock_fallback(env):
    """downweight:<alpha> admits alpha and diverts the complement; a tag
    WITHOUT an origin step falls back to wall-clock age through the
    step-period EWMA."""
    env(BLUEFOG_TPU_ASYNC="1", BLUEFOG_TPU_ASYNC_STALENESS_STEPS="2",
        BLUEFOG_TPU_ASYNC_STALENESS_POLICY="downweight:0.5")
    name, saved = _mk_window()
    try:
        W.configure_async()
        W.set_async_step(100)
        with W._async.lock:
            W._async.step_period = 0.010   # fake clock: 10 ms per step
        row = np.full(5, 4.0, np.float32)
        import time
        old_us = time.time_ns() // 1000 - 50_000   # 50 ms = 5 steps > 2
        W._apply_inbound(
            T.OP_ACCUMULATE | T.OP_TRACE_FLAG, "async_w", 1, 0, 1.0, 0.0,
            row.tobytes() + T.TRACE_TRAILER.pack(1, 1, 0, old_us, -1))
        win = W._store.get(name)
        np.testing.assert_array_equal(win.staging[(0, 1)], row * 0.5)
        np.testing.assert_array_equal(win.stale_residual[(0, 1)], row * 0.5)
        snap = telemetry.snapshot()
        assert snap.get('bf_win_stale_downweighted_total{src="1"}') == 1
    finally:
        _teardown(name, saved)


def test_unsampled_inherits_edge_estimate(env):
    """An untagged contribution on an edge whose last SAMPLED message was
    stale inherits that estimate (staleness is a sender property); on a
    never-sampled edge it is optimistically fresh."""
    env(BLUEFOG_TPU_ASYNC="1", BLUEFOG_TPU_ASYNC_STALENESS_STEPS="3")
    name, saved = _mk_window()
    try:
        W.configure_async()
        W.set_async_step(20)
        win = W._store.get(name)
        row = np.ones(5, np.float32)
        # Never-sampled edge (2 -> 1): untagged is admitted.
        W._apply_inbound(T.OP_ACCUMULATE, name, 2, 1, 1.0, 0.0,
                         row.tobytes())
        np.testing.assert_array_equal(win.staging[(1, 2)], row)
        # Edge 7 -> 0: one stale sample (age 15), then an untagged
        # message — it inherits the stale estimate and is rejected too.
        W._apply_inbound(
            T.OP_ACCUMULATE | T.OP_TRACE_FLAG, name, 7, 0, 1.0, 0.0,
            row.tobytes() + T.TRACE_TRAILER.pack(7, 1, 0, 1, 5))
        W._apply_inbound(T.OP_ACCUMULATE, name, 7, 0, 1.0, 0.0,
                         (row * 7).tobytes())
        np.testing.assert_array_equal(win.staging[(0, 7)],
                                      np.zeros(5, np.float32))
        np.testing.assert_array_equal(win.stale_residual[(0, 7)],
                                      row + row * 7)
    finally:
        _teardown(name, saved)


def test_policy_applies_on_batched_and_native_paths(env):
    """The batched-run and native-folded commit paths enforce the same
    policy as the per-message path."""
    env(BLUEFOG_TPU_ASYNC="1", BLUEFOG_TPU_ASYNC_STALENESS_STEPS="3")
    name, saved = _mk_window()
    try:
        W.configure_async()
        W.set_async_step(50)
        win = W._store.get(name)
        row = np.full(5, 2.0, np.float32)
        stale_tagged = row.tobytes() + T.TRACE_TRAILER.pack(1, 1, 0, 1, 10)
        # Batched path: one fresh put run + one stale accumulate.
        W._apply_inbound_batch([
            (T.OP_PUT, name, 1, 0, 1.0, 0.0, row.tobytes()),
            (T.OP_ACCUMULATE | T.OP_TRACE_FLAG, name, 1, 0, 1.0, 0.0,
             stale_tagged),
        ])
        np.testing.assert_array_equal(win.staging[(0, 1)], row)  # put only
        np.testing.assert_array_equal(win.stale_residual[(0, 1)], row)
        # Native-folded path: a non-replace entry with a stale trace.
        W._commit_native_run(name, [
            (name, False, 2, 1, 0.0, 0, 1, row * 3, row.nbytes,
             (2, 5, 0, 1, 40)),
        ])
        np.testing.assert_array_equal(win.staging[(1, 2)],
                                      np.zeros(5, np.float32))
        np.testing.assert_array_equal(win.stale_residual[(1, 2)], row * 3)
        snap = telemetry.snapshot()
        assert snap.get('bf_win_stale_rejected_total{src="1"}') == 1
        assert snap.get('bf_win_stale_rejected_total{src="2"}') == 1
    finally:
        _teardown(name, saved)


def test_async_off_is_inert(env):
    """ASYNC=0 (default): arbitrarily old tags are admitted untouched —
    the policy machinery never engages (the bitwise-legacy guarantee)."""
    env(BLUEFOG_TPU_ASYNC=None, BLUEFOG_TPU_ASYNC_STALENESS_STEPS="1")
    name, saved = _mk_window()
    try:
        W.configure_async()
        win = W._store.get(name)
        row = np.full(5, 3.0, np.float32)
        W._apply_inbound(
            T.OP_ACCUMULATE | T.OP_TRACE_FLAG, name, 1, 0, 1.0, 0.0,
            row.tobytes() + T.TRACE_TRAILER.pack(1, 1, 0, 1, 0))
        np.testing.assert_array_equal(win.staging[(0, 1)], row)
        assert not win.stale_residual
        assert not [k for k in telemetry.snapshot()
                    if k.startswith("bf_win_stale")]
    finally:
        _teardown(name, saved)


# ---------------------------------------------------------------------------
# Mass conservation (the tested push-sum invariant)
# ---------------------------------------------------------------------------

def test_mass_conservation_random_policy_sequence(env):
    """Under a random mix of fresh/rejected/downweighted accumulates,
    staging + stale residual == total input mass at every point (value
    AND associated-P), and win_fold_stale_residuals restores everything
    into staging exactly."""
    env(BLUEFOG_TPU_ASYNC="1", BLUEFOG_TPU_ASYNC_STALENESS_STEPS="5",
        BLUEFOG_TPU_ASYNC_STALENESS_POLICY="downweight:0.5")
    name, saved = _mk_window(dim=4)
    W.turn_on_win_ops_with_associated_p()
    try:
        W.configure_async()
        W.set_async_step(1000)
        win = W._store.get(name)
        rng = np.random.RandomState(17)
        key = (0, 1)
        total = np.zeros(4, np.float64)
        p_total = 0.0
        for i in range(40):
            # Powers of two keep alpha=0.5 splits and the running sums
            # exact in f32/f64 — the invariant is tested BITWISE.
            row = (2.0 ** rng.randint(-2, 3, size=4)).astype(np.float32)
            age = int(rng.randint(0, 12))       # mix: fresh and stale
            p_w = float(2.0 ** rng.randint(-3, 2))
            W._apply_inbound(
                T.OP_ACCUMULATE | T.OP_TRACE_FLAG, name, 1, 0, 1.0, p_w,
                row.tobytes() + T.TRACE_TRAILER.pack(1, i + 1, 0, 1,
                                                     1000 - age))
            total += row
            p_total += p_w
            with win.lock:
                have = win.staging[key].astype(np.float64) + \
                    win.stale_residual.get(
                        key, np.zeros(4, np.float32)).astype(np.float64)
                p_have = win.p_staging[key] + \
                    win.p_stale_residual.get(key, 0.0)
            np.testing.assert_array_equal(have, total)
            assert p_have == p_total
        assert win.stale_residual, "sequence never triggered the policy"
        folded = W.win_fold_stale_residuals(name)
        assert folded == 1
        np.testing.assert_array_equal(
            win.staging[key].astype(np.float64), total)
        assert win.p_staging[key] == p_total
        assert not win.stale_residual and not win.p_stale_residual
    finally:
        W.turn_off_win_ops_with_associated_p()
        _teardown(name, saved)


def test_stale_residual_survives_state_dict_roundtrip(env):
    env(BLUEFOG_TPU_ASYNC="1", BLUEFOG_TPU_ASYNC_STALENESS_STEPS="1")
    name, saved = _mk_window()
    try:
        W.configure_async()
        W.set_async_step(10)
        row = np.full(5, 6.0, np.float32)
        W._apply_inbound(
            T.OP_ACCUMULATE | T.OP_TRACE_FLAG, name, 1, 0, 1.0, 0.0,
            row.tobytes() + T.TRACE_TRAILER.pack(1, 1, 0, 1, 0))
        snap = W.win_state_dict(name)
        assert "0:1" in snap["stale_residual"]
        win = W._store.get(name)
        with win.lock:
            win.stale_residual.clear()
            win.p_stale_residual.clear()
        W.win_load_state_dict(name, snap)
        np.testing.assert_array_equal(win.stale_residual[(0, 1)], row)
        # Snapshots predating async mode restore cleanly too.
        legacy = {k: v for k, v in snap.items()
                  if k not in ("stale_residual", "p_stale_residual")}
        W.win_load_state_dict(name, legacy)
        assert not win.stale_residual
    finally:
        _teardown(name, saved)


# ---------------------------------------------------------------------------
# Equivalence oracle: ASYNC=1 @ bound infinity == legacy, bitwise
# ---------------------------------------------------------------------------

def _run_pushsum(steps=8, auto_collect_rounds=2):
    bf.init(lambda: topo.RingGraph(8, connect_style=1))
    opt = bf.optim.DistributedPushSumOptimizer(
        optax.sgd(0.05), auto_collect_rounds=auto_collect_rounds)
    params = {"w": np.random.RandomState(3).randn(8, 6).astype(np.float32)}
    state = opt.init(params)
    traj = []
    for _ in range(steps):
        grads = {"w": np.asarray(params["w"]) * np.float32(0.1)}
        params, state = opt.step(params, grads, state)
        traj.append(np.asarray(params["w"]).copy())
    out = np.asarray(opt.debias(params)["w"]).copy()
    opt.free()
    return traj, out


def test_equivalence_oracle_bitwise(env):
    """ASYNC=1 with staleness bound infinity (0) and a collect cadence
    equal to the legacy fence cadence is BITWISE identical to the legacy
    lockstep path, and ASYNC=0 reproduces itself exactly."""
    env(BLUEFOG_TPU_ASYNC=None)
    legacy_traj, legacy_out = _run_pushsum()
    legacy2_traj, _ = _run_pushsum()
    env(BLUEFOG_TPU_ASYNC="1", BLUEFOG_TPU_ASYNC_STALENESS_STEPS="0",
        BLUEFOG_TPU_ASYNC_COLLECT_EVERY="2")
    async_traj, async_out = _run_pushsum(auto_collect_rounds=2)
    for i, (a, b, c) in enumerate(zip(legacy_traj, legacy2_traj,
                                      async_traj)):
        np.testing.assert_array_equal(a, b, err_msg=f"legacy step {i}")
        np.testing.assert_array_equal(a, c, err_msg=f"async step {i}")
    np.testing.assert_array_equal(legacy_out, async_out)


def test_winput_async_implies_overlap(env):
    """ASYNC=1 makes the put family step without waiting on its puts
    (the overlap path), and convergence survives."""
    env(BLUEFOG_TPU_ASYNC="1", BLUEFOG_TPU_ASYNC_COLLECT_EVERY="0")
    bf.init(lambda: topo.ExponentialGraph(8))
    opt = bf.optim.DistributedWinPutOptimizer(optax.sgd(0.2))
    assert not opt.overlap
    params = {"w": np.random.RandomState(5).randn(8, 4).astype(np.float32)}
    state = opt.init(params)
    targets = np.arange(8, dtype=np.float32)[:, None]
    for _ in range(60):
        grads = {"w": np.asarray(params["w"]) - targets}
        params, state = opt.step(params, grads, state)
    # The overlap path engaged: the last step's puts are still pending
    # (a non-async, non-overlap optimizer always waits them out).
    assert opt._pending
    w = np.asarray(params["w"])
    opt.free()
    spread = np.abs(w - w.mean(axis=0, keepdims=True)).max()
    assert spread < 1.0, f"async win-put failed to mix: spread {spread}"


# ---------------------------------------------------------------------------
# Churn soundness: legitimate run-ahead must not read as straggling
# ---------------------------------------------------------------------------

def _controller(env_set, **cfg_env):
    from bluefog_tpu.ops.membership import MembershipController
    env_set(BLUEFOG_TPU_CHURN="1", **cfg_env)
    return MembershipController(
        n_procs=3, my_proc=0, rank_owner={0: 0, 1: 1, 2: 2},
        send_fn=lambda p, b: None, probe_fn=lambda p: True)


def test_straggler_threshold_widens_in_async_mode(env):
    sync = _controller(env, BLUEFOG_TPU_CHURN_STRAGGLER_STEPS="10",
                       BLUEFOG_TPU_ASYNC=None)
    assert sync._straggler_bound() == 10
    wide = _controller(env, BLUEFOG_TPU_CHURN_STRAGGLER_STEPS="10",
                       BLUEFOG_TPU_ASYNC="1",
                       BLUEFOG_TPU_ASYNC_COLLECT_EVERY="40")
    assert wide._straggler_bound() == 50
    off = _controller(env, BLUEFOG_TPU_CHURN_STRAGGLER_STEPS="10",
                      BLUEFOG_TPU_ASYNC="1",
                      BLUEFOG_TPU_ASYNC_COLLECT_EVERY="0")
    assert off._straggler_bound() == 0
    none = _controller(env, BLUEFOG_TPU_CHURN_STRAGGLER_STEPS="0",
                       BLUEFOG_TPU_ASYNC="1")
    assert none._straggler_bound() == 0


def test_async_lag_within_backstop_not_suspected(env):
    """A peer lagging more than CHURN_STRAGGLER_STEPS but less than the
    widened async bound stays un-suspected; beyond the widened bound the
    eviction policy still fires."""
    c = _controller(env, BLUEFOG_TPU_CHURN_STRAGGLER_STEPS="10",
                    BLUEFOG_TPU_ASYNC="1",
                    BLUEFOG_TPU_ASYNC_COLLECT_EVERY="40")
    now = c.now_fn()
    c.last_seen = {1: now, 2: now}
    c.note_step(100)
    c.peer_step = {1: 70, 2: 30}    # lag 30 (legit) and 70 (over bound)
    suspects = c._suspects(now)
    assert 1 not in suspects and 2 in suspects


# ---------------------------------------------------------------------------
# Telemetry surfaces + hygiene
# ---------------------------------------------------------------------------

def test_healthz_async_block_and_hygiene(env):
    env(BLUEFOG_TPU_ASYNC="1", BLUEFOG_TPU_ASYNC_STALENESS_STEPS="3",
        BLUEFOG_TPU_ASYNC_COLLECT_EVERY="16")
    name, saved = _mk_window()
    try:
        W.configure_async()
        W.set_async_step(7)
        row = np.ones(5, np.float32)
        W._apply_inbound(
            T.OP_ACCUMULATE | T.OP_TRACE_FLAG, name, 1, 0, 1.0, 0.0,
            row.tobytes() + T.TRACE_TRAILER.pack(1, 1, 0, 1, 1))
        body = telemetry.health()
        a = body.get("async")
        assert a and a["step"] == 7 and a["staleness_steps"] == 3
        assert a["collect_every"] == 16
        assert a["step_lag"] == 1 - 7
        assert a["stale_rejected"] == {"1": 1.0}
        from bluefog_tpu.run.cluster_repl import bfstat_text
        assert "[bfstat] async: step 7" in bfstat_text()
        # Churn hygiene: a committed membership change drops the dead
        # rank's estimates + counters.
        W.clear_async_staleness([1])
        assert 1 not in W._async.peer_step
        assert not [k for k in telemetry.snapshot()
                    if k.startswith("bf_win_stale")]
        assert telemetry.health()["async"]["step_lag"] == 0
    finally:
        _teardown(name, saved)


def test_telemetry_off_zero_mutation(env):
    env(BLUEFOG_TPU_ASYNC="1", BLUEFOG_TPU_ASYNC_STALENESS_STEPS="1",
        BLUEFOG_TPU_TELEMETRY="0")
    name, saved = _mk_window()
    try:
        W.configure_async()
        W.set_async_step(10)
        row = np.ones(5, np.float32)
        W._apply_inbound(
            T.OP_ACCUMULATE | T.OP_TRACE_FLAG, name, 1, 0, 1.0, 0.0,
            row.tobytes() + T.TRACE_TRAILER.pack(1, 1, 0, 1, 0))
        win = W._store.get(name)
        # The POLICY still applies (it is state, not telemetry)...
        np.testing.assert_array_equal(win.stale_residual[(0, 1)], row)
        # ...but the registry is untouched.
        assert telemetry.snapshot() == {}
    finally:
        _teardown(name, saved)


def test_step_clock_reaches_wire_tags(env):
    """set_async_step publishes the origin-step both encoders stamp: the
    Python trailer carries it, and a loopback store commit feeds it back
    into the freshest-peer estimate."""
    env(BLUEFOG_TPU_ASYNC="1", BLUEFOG_TPU_TRACE_SAMPLE="1")
    W.configure_async()
    W.set_async_step(123)
    tag = T.make_trace_tag(0)
    assert T.TRACE_TRAILER.unpack(tag)[4] == 123
    from bluefog_tpu import native
    if native.available() and native.has_win_native():
        assert native.lib().bf_trace_step() == 123


# ---------------------------------------------------------------------------
# Full gang (slow tier; `make chaos-smoke` runs the same harness in CI):
# the multi-process CPU convergence test — a real bfrun gang under an
# injected delay fault, sync vs async legs, matched final loss, no
# eviction of the merely-slow rank, async survivor throughput held.
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_delay_scenario_end_to_end():
    import subprocess
    import sys
    r = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.tools", "chaos",
         "--delay-smoke"],
        capture_output=True, text=True, timeout=400)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "chaos delay OK" in r.stdout
