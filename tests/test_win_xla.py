"""Zero-copy XLA window put path (BLUEFOG_TPU_WIN_XLA, ops/xlaffi.py +
native/src/xlacall.cc).

Covers the tentpole's contract surface:
  * the BLUEFOG_TPU_WIN_XLA=0/1 loopback-through-store BITWISE state
    equivalence oracle (with and without associated-P) — same wire
    frames, same staging/versions/P state whether the put rows left
    through the host-staged path or straight off the device buffer;
  * a property test that FFI-fed frames decode identically across the
    dense / bf16 / sparse:<frac> codecs (including the sender-side
    error-feedback residual sequence);
  * auto-disarm on a jax stub without jax.ffi (one warning, puts fall
    back, nothing raises);
  * the in-program ``bf_xla_win_put`` custom-call lowering;
  * the ctypes-fallback send heuristic (tobytes below the threshold,
    raw pointer above — satellite of this PR);
  * the ``bf_win_host_copy_bytes_total{path}`` staging-copy oracle:
    zero put-side bytes on the FFI leg for dense f32 rows.
"""

import ctypes
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bluefog_tpu as bf
from bluefog_tpu import native
from bluefog_tpu import topology as topo
from bluefog_tpu.ops import transport as T
from bluefog_tpu.ops import window as W
from bluefog_tpu.ops import xlaffi
from bluefog_tpu.utils import config, telemetry

needs_xla = pytest.mark.skipif(
    not (native.available() and native.has_win_xla()),
    reason="native core lacks the bf_xla symbols")
needs_handler = pytest.mark.skipif(
    not native.has_xla_handler(),
    reason="build lacks the XLA FFI handler (jaxlib headers absent)")


@pytest.fixture
def xla_env(monkeypatch):
    """Set knobs, reload config, and reset every xlaffi cache after."""
    def set_env(**kv):
        for k, v in kv.items():
            monkeypatch.setenv(k, str(v))
        config.reload()
        xlaffi._reset_for_tests()
    yield set_env
    config.reload()
    xlaffi._reset_for_tests()


# ---------------------------------------------------------------------------
# Satellite: ctypes-fallback send heuristic
# ---------------------------------------------------------------------------

def test_ctypes_payload_threshold():
    """Below CTYPES_PTR_BYTES the ctypes fallback ships bytes (cheapest
    conversion, copy ~free); at/above it, the raw data pointer (the copy
    would dwarf the ~µs pointer extraction)."""
    small = np.arange(64, dtype=np.float32)
    arg, nbytes, keep = T._ctypes_payload(small)
    assert isinstance(arg, bytes) and nbytes == small.nbytes
    assert arg == small.tobytes()

    big = np.zeros(T.CTYPES_PTR_BYTES // 4, dtype=np.float32)
    assert big.nbytes >= T.CTYPES_PTR_BYTES
    arg, nbytes, keep = T._ctypes_payload(big)
    assert isinstance(arg, int) and arg == big.ctypes.data
    assert nbytes == big.nbytes and keep is big

    # Non-contiguous input: materialized first, then the same rule.
    strided = np.zeros((2, T.CTYPES_PTR_BYTES // 4), np.float32)[:, ::2]
    arg, nbytes, keep = T._ctypes_payload(strided)
    assert isinstance(arg, int)
    assert keep.flags.c_contiguous and nbytes == keep.nbytes


@needs_xla
def test_ctypes_pointer_path_delivers(xla_env):
    """A pointer-path payload (>= CTYPES_PTR_BYTES) arrives bit-identical
    through the native sender even with the fastcall module bypassed."""
    xla_env(BLUEFOG_TPU_WIN_COALESCE=1, BLUEFOG_TPU_WIN_NATIVE=1,
            BLUEFOG_TPU_WIN_COALESCE_LINGER_MS=200)
    got = []
    ev = threading.Event()

    def apply(op, name, src, dst, weight, p_weight, payload):
        got.append(bytes(payload))
        ev.set()

    server = T.WindowTransport(apply)
    client = T.WindowTransport(lambda *a: None)
    try:
        assert client.native_path
        client._fc_send = None  # force the ctypes fallback
        row = np.random.RandomState(0).randn(
            T.CTYPES_PTR_BYTES // 4 + 16).astype(np.float32)
        client.send("127.0.0.1", server.port, T.OP_PUT, "big", 0, 1, 1.0,
                    row)
        client.flush()
        assert ev.wait(20)
        assert got[0] == row.tobytes()
    finally:
        client.stop()
        server.stop()


# ---------------------------------------------------------------------------
# FFI-fed frames: codec property test
# ---------------------------------------------------------------------------

def _plan_lib():
    lib = native.lib()
    return lib


@needs_xla
@pytest.mark.parametrize("codec", ["none", "bf16", "sparse:0.4"])
def test_ffi_frames_decode_identically_across_codecs(xla_env, codec):
    """Frames fed by the native plan executor decode (through the Python
    drain) to EXACTLY the payload bytes the Python encoder produces for
    the same rows — dense raw, bf16 round-to-nearest-even, and the
    sparse error-feedback sequence (3 successive sends per edge, so the
    residual fold is exercised, not just the first selection)."""
    xla_env(BLUEFOG_TPU_WIN_COALESCE=1, BLUEFOG_TPU_WIN_NATIVE=0,
            BLUEFOG_TPU_WIN_COALESCE_LINGER_MS=200)
    lib = _plan_lib()
    elems, rounds = 11, 3
    name = f"cx_{codec.replace(':', '_').replace('.', '_')}"
    op = T.OP_ACCUMULATE
    codec_id = {"none": 0, "bf16": 1}.get(codec, 2)
    frac = 0.4 if codec.startswith("sparse") else 1.0

    got = []
    cv = threading.Condition()

    def apply(oper, nm, src, dst, weight, p_weight, payload):
        with cv:
            got.append((oper, nm, src, dst, weight, p_weight,
                        bytes(payload)))
            cv.notify_all()

    server = T.WindowTransport(apply)
    client = T.WindowTransport(lambda *a: None)
    try:
        # The native tx is required for plan dispatch even when the
        # server decodes in Python (the decode side is what's under
        # test here).
        assert client._tx is None  # WIN_NATIVE=0 pins the Python sender
        xla_env(BLUEFOG_TPU_WIN_COALESCE=1, BLUEFOG_TPU_WIN_NATIVE=1,
                BLUEFOG_TPU_WIN_COALESCE_LINGER_MS=200)
        client2 = T.WindowTransport(lambda *a: None)
        assert client2.native_path
        rng = np.random.RandomState(7)
        rows = [rng.randn(2, elems).astype(np.float32)
                for _ in range(rounds)]
        lib.bf_xla_drop_residuals(None)
        W._drop_ef_residuals()
        plan = lib.bf_xla_plan_new(name.encode(), elems, 2, codec_id, frac)
        assert plan > 0
        for i, (src, dst) in enumerate([(0, 1), (0, 2)]):
            assert lib.bf_xla_plan_edge(
                plan, i, b"127.0.0.1", server.port, op, src, dst,
                0.25 * (i + 1), i, 0) == 0
        total = 0
        for r in range(rounds):
            data = np.ascontiguousarray(rows[r])
            rc = lib.bf_xla_plan_run(plan, client2._tx, data.ctypes.data,
                                     data.size)
            assert rc == 0
            client2.flush()
            total += 2
        with cv:
            assert cv.wait_for(lambda: len(got) >= total, timeout=30)
        lib.bf_xla_plan_free(plan)
        client2.stop()

        # Reference: the Python encoder on the same row sequence.
        expect = []
        for r in range(rounds):
            for i, (src, dst) in enumerate([(0, 1), (0, 2)]):
                row = np.ascontiguousarray(rows[r][i])
                if codec == "bf16":
                    payload = row.astype(np.dtype(jnp.bfloat16)).tobytes()
                    eop = op | T.OP_BF16_FLAG
                elif codec.startswith("sparse"):
                    # Reference residual stream keyed off a DIFFERENT
                    # window name: _sparse_payload now folds in any
                    # native residual for its key (the cross-store
                    # hand-off), and the native sequence above already
                    # populated this name's native store.
                    payload = W._sparse_payload(
                        "ref_" + name, src, dst, row, frac).tobytes()
                    eop = op | T.OP_SPARSE_FLAG
                else:
                    payload = row.tobytes()
                    eop = op
                expect.append((eop, name, src, dst, 0.25 * (i + 1),
                               payload))
        assert len(got) == len(expect)
        for (g, e) in zip(got, expect):
            assert g[0] == e[0], "op byte (codec flag)"
            assert (g[1], g[2], g[3]) == (e[1], e[2], e[3])
            assert g[4] == e[4], "wire weight"
            assert g[6] == e[5], "payload bytes (bitwise)"
            if codec.startswith("sparse"):
                gi, gv = T.sparse_decode(g[6])
                ei, ev = T.sparse_decode(e[5])
                np.testing.assert_array_equal(gi, ei)
                np.testing.assert_array_equal(gv, ev)
    finally:
        W._drop_ef_residuals()
        try:
            client2.stop()
        except Exception:
            pass
        client.stop()
        server.stop()


# ---------------------------------------------------------------------------
# Loopback-through-store equivalence oracle (the =0/=1 contract)
# ---------------------------------------------------------------------------

def _fake_distrib(transport, server_port):
    """Rank directory for the loopback store: even ranks owned here
    (proc 0), odd ranks 'owned' by proc 1 — whose endpoint is the local
    server transport feeding the SAME store (the window was created
    before the directory install, so it carries every rank's slots)."""
    return W._Distrib(transport,
                      rank_owner={r: r % 2 for r in range(8)},
                      proc_addr={0: ("127.0.0.1", 1),
                                 1: ("127.0.0.1", server_port)},
                      my_proc=0)


def _drive_xla_store(xla_env, use_xla, with_p, codec="none"):
    """One deterministic put/accumulate stream of DEVICE arrays through
    the real window-op path into a loopback store; returns the window
    state snapshot (the =0/=1 oracle drives this twice)."""
    bf.init(lambda: topo.RingGraph(8))
    xla_env(BLUEFOG_TPU_WIN_COALESCE=1,
            BLUEFOG_TPU_WIN_COALESCE_LINGER_MS=500,
            BLUEFOG_TPU_WIN_NATIVE=1,
            BLUEFOG_TPU_WIN_XLA=1 if use_xla else 0,
            BLUEFOG_TPU_WIN_COMPRESSION=codec)
    if with_p:
        bf.turn_on_win_ops_with_associated_p()
    rng = np.random.RandomState(23)
    x = rng.randn(8, 6).astype(np.float32)
    applied = [0]
    cv = threading.Condition()

    def bump(k):
        with cv:
            applied[0] += k
            cv.notify_all()

    def apply(op, name, src, dst, weight, p_weight, payload):
        W._apply_inbound(op, name, src, dst, weight, p_weight, payload)
        bump(1)

    def apply_batch(msgs):
        W._apply_inbound_batch(msgs)
        bump(len(msgs))

    def apply_items(items):
        W._apply_inbound_items(items)
        bump(sum((p[5] + p[6]) if k else 1 for k, p in items))

    server = T.WindowTransport(apply, apply_batch=apply_batch,
                               apply_items=apply_items)
    client = T.WindowTransport(lambda *a: None)
    saved = W._store.distrib
    try:
        assert client.native_path, "native sender required for both legs"
        assert bf.win_create(x, "xeq", zero_init=True)
        server.register_window("xeq", 6)
        W._store.distrib = _fake_distrib(client, server.port)
        if use_xla:
            assert xlaffi.armed(), xlaffi.disarm_reason()
        total = 0
        for step in range(6):
            srng = np.random.RandomState(300 + step)
            t = jnp.asarray(srng.randn(8, 6).astype(np.float32))
            # The (bidirectional) ring's out-edges from owned (even) srcs
            # all target odd dsts: 8 remote edges per op.
            if step % 2:
                bf.win_accumulate(t, "xeq",
                                  self_weight=0.5 if step == 3 else None,
                                  require_mutex=False)
            else:
                bf.win_put(t, "xeq", require_mutex=False)
            total += 8
            with cv:
                assert cv.wait_for(lambda: applied[0] >= total,
                                   timeout=30), (applied[0], total)
        if use_xla:
            snap = telemetry.snapshot()
            assert any(k.startswith("bf_win_xla_puts_total")
                       for k in snap), "FFI path did not engage"
        return bf.win_state_dict("xeq")
    finally:
        W._store.distrib = saved
        bf.win_free("xeq")
        client.stop()
        server.stop()
        if with_p:
            bf.turn_off_win_ops_with_associated_p()


@needs_xla
@pytest.mark.parametrize("with_p", [False, True])
@pytest.mark.parametrize("codec", ["none", "bf16", "sparse:0.5"])
def test_xla_vs_host_path_state_equivalence_bitwise(xla_env, with_p,
                                                    codec):
    """The BLUEFOG_TPU_WIN_XLA=0/1 oracle: the same device-array put
    stream lands BIT-IDENTICAL window state — staging rows, version
    counters, associated-P — whether the rows left through the
    host-staged PR-9 path or straight off the XLA buffer, across every
    wire codec (sparse rides accumulate edges with unique-magnitude
    random rows, so the top-k selection is deterministic on both
    sides)."""
    ffi = _drive_xla_store(xla_env, use_xla=True, with_p=with_p,
                           codec=codec)
    host = _drive_xla_store(xla_env, use_xla=False, with_p=with_p,
                            codec=codec)
    for part in ("staging", "versions", "p_staging", "main", "p_main"):
        assert set(host[part]) == set(ffi[part]), part
        for k, v in host[part].items():
            np.testing.assert_array_equal(
                np.asarray(ffi[part][k]), np.asarray(v),
                err_msg=f"{part}[{k}] (bitwise)")


@needs_xla
def test_xla_put_zero_staging_copies_dense(xla_env):
    """The staging-copy oracle: a dense-f32 FFI-fed put stream reports
    ZERO put-side bytes in bf_win_host_copy_bytes_total (device_get /
    edge_temp / enqueue all bypassed)."""
    telemetry.reset()
    _drive_xla_store(xla_env, use_xla=True, with_p=False)
    snap = telemetry.snapshot()
    for path in ("device_get", "edge_temp", "enqueue"):
        key = f'bf_win_host_copy_bytes_total{{path="{path}"}}'
        assert snap.get(key, 0) == 0, (key, snap.get(key))


@needs_xla
def test_host_path_reports_staging_copies(xla_env):
    """The same stream through the Python coalesced sender DOES count
    enqueue copies — the counter is live, not trivially zero."""
    telemetry.reset()
    bf.init(lambda: topo.RingGraph(8))
    xla_env(BLUEFOG_TPU_WIN_COALESCE=1, BLUEFOG_TPU_WIN_NATIVE=0,
            BLUEFOG_TPU_WIN_XLA=0,
            BLUEFOG_TPU_WIN_COALESCE_LINGER_MS=200)
    done = threading.Event()

    def apply(*a):
        done.set()

    server = T.WindowTransport(apply)
    client = T.WindowTransport(lambda *a: None)
    saved = W._store.distrib
    x = np.zeros((8, 6), np.float32)
    try:
        assert bf.win_create(x, "hc", zero_init=True)
        W._store.distrib = _fake_distrib(client, server.port)
        bf.win_put(jnp.asarray(x), "hc", require_mutex=False)
        assert done.wait(20)
        snap = telemetry.snapshot()
        assert snap.get('bf_win_host_copy_bytes_total{path="enqueue"}',
                        0) > 0
    finally:
        W._store.distrib = saved
        bf.win_free("hc")
        client.stop()
        server.stop()


# ---------------------------------------------------------------------------
# Auto-disarm (jax stub without jax.ffi) and arming diagnostics
# ---------------------------------------------------------------------------

def test_auto_disarm_without_jax_ffi(xla_env, monkeypatch, caplog):
    """On a jax without jax.ffi/jax.extend.ffi the path disarms with one
    warning, keep_device_ok refuses device arrays, and a put still works
    through the fallback."""
    from bluefog_tpu import _compat
    xla_env(BLUEFOG_TPU_WIN_XLA=1)
    monkeypatch.setattr(_compat, "jax_ffi", lambda: None)
    xlaffi._reset_for_tests()
    assert not xlaffi.armed()
    assert "no jax.ffi" in (xlaffi.disarm_reason() or "")
    # The one-shot warning fired (the bluefog logger does not propagate
    # to caplog, so assert on the module's one-shot latch instead).
    assert xlaffi._warned
    config.reload()
    assert not xlaffi.armed()
    assert xlaffi._warned
    # Puts fall back to the host path and still work (single-process).
    bf.init(lambda: topo.RingGraph(8))
    x = np.random.RandomState(1).randn(8, 3).astype(np.float32)
    assert bf.win_create(x, "dz", zero_init=True)
    try:
        win = W._store.get("dz")
        assert not xlaffi.keep_device_ok(jnp.asarray(x), win)
        assert bf.win_put(jnp.asarray(x), "dz")
        ver = bf.get_win_version("dz")
        assert any(v > 0 for v in ver.values())
    finally:
        bf.win_free("dz")


def test_disarm_reason_on_knob_off(xla_env):
    xla_env(BLUEFOG_TPU_WIN_XLA=0)
    assert not xlaffi.armed()
    assert xlaffi.disarm_reason() == "BLUEFOG_TPU_WIN_XLA=0"
    info = bf.win_xla_info()
    assert info["armed"] is False and info["reason"]


# ---------------------------------------------------------------------------
# In-program lowering (bf_xla_win_put custom call)
# ---------------------------------------------------------------------------

@needs_handler
def test_in_program_ffi_put(xla_env):
    """The put lowered INTO a jitted program: the XLA custom call runs
    the same native plan mid-program and the rows arrive bit-identical
    at the peer."""
    xla_env(BLUEFOG_TPU_WIN_COALESCE=1, BLUEFOG_TPU_WIN_NATIVE=1,
            BLUEFOG_TPU_WIN_COALESCE_LINGER_MS=200)
    lib = _plan_lib()
    got = []
    cv = threading.Condition()

    def apply(op, name, src, dst, weight, p_weight, payload):
        with cv:
            got.append((src, dst, bytes(payload)))
            cv.notify_all()

    server = T.WindowTransport(apply)
    client = T.WindowTransport(lambda *a: None)
    try:
        assert client.native_path
        plan = lib.bf_xla_plan_new(b"jitw", 5, 2, 0, 1.0)
        for i, (src, dst) in enumerate([(0, 1), (0, 3)]):
            assert lib.bf_xla_plan_edge(plan, i, b"127.0.0.1", server.port,
                                        T.OP_PUT, src, dst, 1.0, i, 0) == 0
        run = xlaffi.xla_put_program(plan, client._tx)
        assert run is not None

        @jax.jit
        def step(x):
            st = run(x)
            return x * 2.0, st

        x = jnp.asarray(np.random.RandomState(3).randn(2, 5)
                        .astype(np.float32))
        y, st = step(x)
        assert int(np.asarray(st)[0]) == 0
        client.flush()
        with cv:
            assert cv.wait_for(lambda: len(got) >= 2, timeout=30)
        xh = np.asarray(x)
        assert got[0] == (0, 1, xh[0].tobytes())
        assert got[1] == (0, 3, xh[1].tobytes())
        np.testing.assert_array_equal(np.asarray(y), xh * 2.0)
        lib.bf_xla_plan_free(plan)
    finally:
        client.stop()
        server.stop()


# ---------------------------------------------------------------------------
# Commit-side re-entry
# ---------------------------------------------------------------------------

def test_commit_to_jax_values_and_accounting(xla_env):
    """commit_to_jax returns the exact values and, where the runtime
    aliases host arrays (CPU jax), counts no commit copy."""
    xla_env(BLUEFOG_TPU_WIN_XLA=1)
    telemetry.reset()
    arr = np.random.RandomState(5).randn(4, 3).astype(np.float32)
    out = xlaffi.commit_to_jax(arr.copy())
    np.testing.assert_array_equal(np.asarray(out), arr)
    assert xlaffi._commit_mode[0] in ("verify", "dlpack")
    snap = telemetry.snapshot()
    copied = snap.get('bf_win_host_copy_bytes_total{path="commit"}', 0)
    # On this runtime jnp.asarray aliases (or dlpack rescues): zero-copy.
    assert copied in (0, arr.nbytes)


@needs_xla
def test_sparse_residuals_survive_path_switch(xla_env):
    """Error-feedback mass must not strand when one edge's put stream
    switches between the native (FFI) and host encoders: the two
    residual stores hand off additively, so the summed wire mass over
    any mixed sequence equals the summed input mass."""
    xla_env(BLUEFOG_TPU_WIN_COALESCE=1, BLUEFOG_TPU_WIN_NATIVE=1,
            BLUEFOG_TPU_WIN_COALESCE_LINGER_MS=200)
    lib = native.lib()
    elems, frac = 10, 0.3
    name = "resx"
    rng = np.random.RandomState(17)
    rows = [rng.randn(elems).astype(np.float32) for _ in range(4)]
    W._drop_ef_residuals()
    lib.bf_xla_drop_residuals(None)

    got = []
    cv = threading.Condition()

    def apply(op, nm, src, dst, w, pw, payload):
        with cv:
            got.append(bytes(payload))
            cv.notify_all()

    server = T.WindowTransport(apply)
    client = T.WindowTransport(lambda *a: None)
    try:
        assert client.native_path
        plan = lib.bf_xla_plan_new(name.encode(), elems, 1, 2, frac)
        assert lib.bf_xla_plan_edge(plan, 0, b"127.0.0.1", server.port,
                                    T.OP_ACCUMULATE, 0, 1, 1.0, 0, 0) == 0
        wire_mass = np.zeros(elems, np.float64)
        sent_native = 0
        # Alternate: native sends (rounds 0, 2) and host-encoder sends
        # (rounds 1, 3) — each side must fold the other's residual.
        for r, row in enumerate(rows):
            if r % 2 == 0:
                data = np.ascontiguousarray(row)
                assert lib.bf_xla_plan_run(plan, client._tx,
                                           data.ctypes.data, elems) == 0
                client.flush()
                sent_native += 1
                want = sent_native
                with cv:
                    assert cv.wait_for(lambda: len(got) >= want,
                                       timeout=30)
                payload = got[-1]
            else:
                payload = W._sparse_payload(name, 0, 1, row, frac).tobytes()
            idx, vals = T.sparse_decode(payload)
            np.add.at(wire_mass, idx, vals.astype(np.float64))
        # Remaining residual may live in EITHER store; drain both.
        res = np.zeros(elems, np.float64)
        nat = xlaffi.take_native_residual(name, 0, 1, elems)
        if nat is not None:
            res += nat
        with W._ef_lock:
            r = W._ef_residuals.pop((name, 0, 1), None)
        if r is not None:
            res += r
        total_in = np.sum(rows, axis=0, dtype=np.float64)
        np.testing.assert_allclose(wire_mass + res, total_in, rtol=1e-5,
                                   err_msg="mass stranded across stores")
        lib.bf_xla_plan_free(plan)
    finally:
        W._drop_ef_residuals()
        client.stop()
        server.stop()


@needs_xla
def test_plan_p_masses_rezeroed_after_p_disable(xla_env):
    """A cached plan that shipped associated-P masses must ship p=0.0
    on the wire again after turn_off_win_ops_with_associated_p() — the
    host-path oracle's exact wire behavior (stale cached masses would
    silently fold phantom P at any peer whose toggle lags)."""
    bf.init(lambda: topo.RingGraph(8))
    xla_env(BLUEFOG_TPU_WIN_COALESCE=1, BLUEFOG_TPU_WIN_NATIVE=1,
            BLUEFOG_TPU_WIN_XLA=1,
            BLUEFOG_TPU_WIN_COALESCE_LINGER_MS=200)
    wire_p = []
    cv = threading.Condition()

    def apply(op, nm, src, dst, w, pw, payload):
        with cv:
            wire_p.append(float(pw))
            cv.notify_all()

    server = T.WindowTransport(apply)  # raw recorder: no store apply,
    client = T.WindowTransport(lambda *a: None)  # no win registration
    saved = W._store.distrib
    x = np.zeros((8, 4), np.float32)
    try:
        assert bf.win_create(x, "pz", zero_init=True)
        W._store.distrib = _fake_distrib(client, server.port)
        t = jnp.asarray(np.ones((8, 4), np.float32))
        bf.turn_on_win_ops_with_associated_p()
        bf.win_accumulate(t, "pz", require_mutex=False)
        with cv:
            assert cv.wait_for(lambda: len(wire_p) >= 8, timeout=30)
        assert all(p == 1.0 for p in wire_p[:8]), wire_p[:8]
        bf.turn_off_win_ops_with_associated_p()
        bf.win_accumulate(t, "pz", require_mutex=False)
        with cv:
            assert cv.wait_for(lambda: len(wire_p) >= 16, timeout=30)
        assert all(p == 0.0 for p in wire_p[8:16]), wire_p[8:16]
    finally:
        W._store.distrib = saved
        bf.win_free("pz")
        client.stop()
        server.stop()
        bf.turn_off_win_ops_with_associated_p()


def test_optimizer_payloads_stay_on_device_when_armed(xla_env,
                                                      monkeypatch):
    """The window optimizers keep their put payloads as jax arrays (the
    fused concatenate compiles into the step) exactly when the FFI path
    is armed for a multi-process all-f32 tree — and fall back to the
    legacy numpy payloads (bitwise-identical rows) otherwise."""
    from bluefog_tpu.optim import window_optimizers as WO
    import optax
    bf.init(lambda: topo.RingGraph(8))
    opt = WO.DistributedWinPutOptimizer(optax.sgd(0.1))
    opt._rows = 8
    tree = {"a": jnp.ones((8, 3), jnp.float32),
            "b": jnp.zeros((8, 2, 2), jnp.float32)}
    # Single-process (no distrib): legacy numpy payloads.
    assert not opt._device_payloads_ok(tree)
    legacy = opt._payloads(tree)
    assert isinstance(legacy[0], np.ndarray)
    # Fake a live distrib + armed path: payloads stay on device.
    monkeypatch.setattr(W._store, "distrib", object())
    monkeypatch.setattr(xlaffi, "armed", lambda: True)
    assert opt._device_payloads_ok(tree)
    dev = opt._payloads(tree)
    assert isinstance(dev[0], jax.Array) and dev[0].dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(dev[0]), legacy[0])
    # A mixed-dtype tree must NOT take the device path (numpy promotion
    # would differ from jnp's): falls back.
    tree["c"] = jnp.zeros((8, 2), jnp.int32)
    assert not opt._device_payloads_ok(tree)


def test_win_update_returns_usable_array(xla_env):
    """win_update's zero-copy return stays a normal jax array: consumable
    by jnp ops and by the optimizers' _rebuild round-trip."""
    xla_env(BLUEFOG_TPU_WIN_XLA=1)
    bf.init(lambda: topo.RingGraph(8))
    x = np.random.RandomState(2).randn(8, 3).astype(np.float32)
    assert bf.win_create(x, "zc")
    try:
        bf.win_put(x, "zc")
        out = bf.win_update("zc")
        assert isinstance(out, jax.Array)
        _ = jnp.sum(out)  # participates in further jax math
        ref = np.asarray(out)
        assert ref.shape == x.shape
    finally:
        bf.win_free("zc")
