"""Op-level oracle tests for the hierarchical family (DP-5/DP-6).

Closed-form expected values on a (4 machines x 2 local) virtual mesh:
``hierarchical_neighbor_allreduce`` must equal the reference pipeline —
local sum, machine-level weighted combine of the *sums*, divide by
local_size after combining (``mpi_controller.cc:455-515``,
``torch/mpi_ops.cc:416-419``) — the dynamic variant must agree with the
``GetExp2DynamicSendRecvMachineRanks`` walk, and ``local_allreduce`` with
the per-machine mean (``mpi_ops.py:92-104``).
"""

import numpy as np
import pytest

import bluefog_tpu as bf
from bluefog_tpu import topology as topo

N = 8
LOCAL = 2
MACHINES = N // LOCAL


def setup_hier(machine_graph=None, is_weighted=False):
    bf.init(lambda: topo.ExponentialGraph(N), local_size=LOCAL)
    if machine_graph is not None:
        bf.set_machine_topology(machine_graph, is_weighted=is_weighted)


def rank_major(seed=0, shape=(N, 3)):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


def machine_sums(x):
    return np.stack([x[m * LOCAL:(m + 1) * LOCAL].sum(axis=0)
                     for m in range(MACHINES)])


def test_identity_queries():
    setup_hier()
    assert bf.machine_size() == MACHINES
    assert bf.local_size() == LOCAL


def test_local_allreduce_oracle():
    """DP-6: allreduce over the LOCAL axis only — per-machine mean/sum."""
    setup_hier()
    x = rank_major(1)
    out = np.asarray(bf.local_allreduce(x))
    sums = machine_sums(x)
    for r in range(N):
        np.testing.assert_allclose(out[r], sums[r // LOCAL] / LOCAL,
                                   rtol=1e-5)
    out_sum = np.asarray(bf.local_allreduce(x, average=False))
    for r in range(N):
        np.testing.assert_allclose(out_sum[r], sums[r // LOCAL], rtol=1e-5)


def test_hierarchical_neighbor_allreduce_ring_oracle():
    """Static machine ring, uniform weights: every rank of machine m must get
    (S_m + S_{m-1} + S_{m+1}) / 3 / local_size — weighted combine of local
    SUMS with the divide by local_size applied after the combine."""
    setup_hier(topo.RingGraph(MACHINES))
    x = rank_major(2)
    out = np.asarray(bf.hierarchical_neighbor_allreduce(x))
    sums = machine_sums(x)
    for r in range(N):
        m = r // LOCAL
        expect = (sums[m] + sums[(m - 1) % MACHINES]
                  + sums[(m + 1) % MACHINES]) / 3.0 / LOCAL
        np.testing.assert_allclose(out[r], expect, rtol=1e-4)


def test_hierarchical_neighbor_allreduce_explicit_weights():
    """Explicit machine weight matrix: out = (sum_j W[j,m] * S_j) / local."""
    setup_hier(topo.RingGraph(MACHINES))
    w = np.zeros((MACHINES, MACHINES))
    for m in range(MACHINES):
        w[m, m] = 0.6
        w[(m - 1) % MACHINES, m] = 0.3
        w[(m + 1) % MACHINES, m] = 0.1
    x = rank_major(3)
    out = np.asarray(bf.hierarchical_neighbor_allreduce(
        x, src_machine_weights=w))
    sums = machine_sums(x)
    for r in range(N):
        m = r // LOCAL
        expect = (0.6 * sums[m] + 0.3 * sums[(m - 1) % MACHINES]
                  + 0.1 * sums[(m + 1) % MACHINES]) / LOCAL
        np.testing.assert_allclose(out[r], expect, rtol=1e-4)


def test_hierarchical_wrong_order_would_fail():
    """Guard the averaging order: with irregular per-machine data, dividing
    before the machine combine (per-machine mean instead of sum) yields a
    different result than the reference order whenever weights don't sum the
    same way — use non-column-stochastic weights to tell them apart."""
    setup_hier(topo.RingGraph(MACHINES))
    w = np.zeros((MACHINES, MACHINES))
    for m in range(MACHINES):
        w[m, m] = 1.0
        w[(m + 1) % MACHINES, m] = 1.0  # receive raw sum from right neighbor
    x = rank_major(4)
    out = np.asarray(bf.hierarchical_neighbor_allreduce(
        x, src_machine_weights=w))
    sums = machine_sums(x)
    for r in range(N):
        m = r // LOCAL
        expect = (sums[m] + sums[(m + 1) % MACHINES]) / LOCAL
        np.testing.assert_allclose(out[r], expect, rtol=1e-4)


def test_dynamic_hierarchical_matches_exp2_machine_walk():
    """The jitted dynamic hierarchical op agrees with the eager
    GetExp2DynamicSendRecvMachineRanks walk step by step."""
    setup_hier(topo.ExponentialGraph(MACHINES))
    phases = topo.one_peer_exp2_phases(MACHINES)
    x = rank_major(5)
    sums = machine_sums(x)

    walkers = [topo.GetExp2DynamicSendRecvMachineRanks(
        N, LOCAL, m * LOCAL, 0) for m in range(MACHINES)]
    for step in range(6):
        out = np.asarray(bf.dynamic_hierarchical_neighbor_allreduce(
            x, step, phases=phases))
        sends = [next(w) for w in walkers]  # ([send_machine], [recv_machine])
        for r in range(N):
            m = r // LOCAL
            recv_m = sends[m][1][0]
            assert sends[recv_m][0][0] == m, "walk must be permutation"
            expect = (sums[m] + sums[recv_m]) / 2.0 / LOCAL
            np.testing.assert_allclose(out[r], expect, rtol=1e-4)


@pytest.mark.slow
def test_schedule_cache_churn_no_stale_reuse():
    """Churn >128 distinct weight overrides through neighbor_allreduce: the
    FIFO schedule eviction must never let a compiled closure serve a stale
    schedule (VERDICT round-1 weak #6)."""
    bf.init(lambda: topo.RingGraph(N))
    x = rank_major(6)
    from bluefog_tpu import basics
    limit = basics._Context.MAX_CACHED_SCHEDULES

    def weights_for(i):
        w = np.zeros((N, N))
        a = 0.1 + 0.8 * i / (limit + 40.0)  # all distinct: guarantees churn
        for r in range(N):
            w[r, r] = a
            w[(r - 1) % N, r] = (1 - a) / 2
            w[(r + 1) % N, r] = (1 - a) / 2
        return w

    def expected(i):
        w = weights_for(i)
        return np.stack([
            sum(w[s, d] * x[s] for s in range(N) if w[s, d]) for d in range(N)])

    for i in range(limit + 40):
        out = np.asarray(bf.neighbor_allreduce(x, src_weights=weights_for(i)))
        np.testing.assert_allclose(out, expected(i), rtol=1e-4)
    # revisit early (long-evicted) keys: must recompile fresh, not reuse
    for i in (0, 1, 2):
        out = np.asarray(bf.neighbor_allreduce(x, src_weights=weights_for(i)))
        np.testing.assert_allclose(out, expected(i), rtol=1e-4)
    n_sched = len(basics._ctx._static_scheds)
    assert n_sched <= limit, n_sched
