"""Flash attention kernel tests (interpreter mode on CPU) vs dense oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bluefog_tpu.models.transformer import local_attention
from bluefog_tpu.ops.flash_attention import flash_attention

B, S, H, D = 2, 64, 2, 16


@pytest.fixture
def qkv():
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("block", [16, 32, 64])
def test_flash_matches_dense(qkv, causal, block):
    q, k, v = qkv
    ref = local_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=block,
                          block_k=block)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_grads_match_dense(qkv, causal):
    q, k, v = qkv

    def loss_dense(q, k, v):
        return jnp.sum(local_attention(q, k, v, causal=causal) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, block_q=16,
                                       block_k=16) ** 2)

    g_ref = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_out, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3)


def test_flash_uneven_blocks(qkv):
    q, k, v = qkv
    ref = local_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_flash_inside_ulysses(devices, qkv):
    """flash kernel as the inner attention of Ulysses sequence parallelism."""
    from jax.sharding import Mesh, PartitionSpec as P

    from bluefog_tpu.ops.flash_attention import flash_attention_impl
    from bluefog_tpu.parallel import ulysses_attention

    q, k, v = qkv
    ref = local_attention(q, k, v, causal=True)
    mesh = Mesh(np.asarray(devices[:2]), ("sp",))
    out = jax.jit(jax.shard_map(
        lambda a, b, c: ulysses_attention(
            a, b, c, axis_name="sp", causal=True,
            inner_attention=flash_attention_impl(block_q=16, block_k=16)),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3,
        out_specs=P(None, "sp"), check_vma=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_flash_bf16(qkv):
    q, k, v = (t.astype(jnp.bfloat16) for t in qkv)
    ref = local_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=5e-2, atol=5e-2)


@pytest.mark.slow
def test_flash_compiled_mosaic_on_tpu():
    """Run the ACTUAL Mosaic kernel (interpret=False) fwd+bwd against dense
    on real TPU hardware.  The in-process suite pins the CPU backend, so this
    drives a clean subprocess; skipped when no TPU is attached."""
    import os
    import subprocess
    import sys
    from conftest import tpu_subprocess_env
    env = tpu_subprocess_env()  # skip on outage/no-TPU, FAIL on broken env
    probe = """
import jax, jax.numpy as jnp, numpy as np, sys
if jax.default_backend() != "tpu":
    print("NO-TPU"); sys.exit(0)
from bluefog_tpu.ops.flash_attention import flash_attention
B, S, H, D = 1, 512, 4, 64
q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.bfloat16)
           for kk in jax.random.split(jax.random.PRNGKey(0), 3))
def dense(q, k, v):
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / np.sqrt(D)
    s = jnp.where(jnp.tril(jnp.ones((S, S), bool))[None, None], s, -1e30)
    return jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1),
                      v.astype(jnp.float32))
out = jax.jit(lambda q, k, v: flash_attention(q, k, v, interpret=False))(q, k, v)
err = float(jnp.abs(out.astype(jnp.float32) - dense(q, k, v)).max())
assert err < 0.05, f"fwd err {err}"
gf = jax.jit(jax.grad(lambda q: flash_attention(
    q, k, v, interpret=False).astype(jnp.float32).sum()))(q)
gd = jax.grad(lambda q: dense(q, k, v).sum())(q)
gerr = float(jnp.abs(gf.astype(jnp.float32) - gd.astype(jnp.float32)).max())
assert gerr < 0.1, f"bwd err {gerr}"
print("MOSAIC-OK", err, gerr)
"""
    out = subprocess.run([sys.executable, "-c", probe], env=env,
                         capture_output=True, text=True, timeout=560)
    assert out.returncode == 0, f"stdout={out.stdout}\nstderr={out.stderr}"
    if "NO-TPU" in out.stdout:
        pytest.skip("no TPU attached")
    assert "MOSAIC-OK" in out.stdout, out.stdout
