"""Globally-sharded (GSPMD tensor-parallel) checkpoint/resume under real
multi-process launch (VERDICT r3 next-round #3).

The fast tier's checkpoint tests cover process-local and replicated state;
these cover the case round 3 rejected outright: a jax.Array whose shards
live on OTHER processes.  Every process writes its own shards into ONE
coordinated orbax checkpoint and restores only its own shards back — the
tensor-parallel LM state never materializes on a single host.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.slow


def _run_bfrun(tmp_path, script_text: str, np_procs: int, devices: int,
               timeout: int = 600) -> str:
    script = tmp_path / "prog.py"
    script.write_text(script_text.replace("@REPO@", REPO)
                      .replace("@TMP@", str(tmp_path)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.run", "-np", str(np_procs),
         "--devices-per-proc", str(devices), sys.executable, str(script)],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env)
    assert out.returncode == 0, \
        f"stdout={out.stdout}\nstderr={out.stderr[-4000:]}"
    return out.stdout


_SHARDED_CKPT_SCRIPT = r"""
import sys
sys.path.insert(0, "@REPO@")
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import optax
import bluefog_tpu as bf
from bluefog_tpu.utils import checkpoint
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

bf.init_distributed()
mesh = Mesh(np.array(jax.devices()), ("tp",))
D, H = 8, 32
rng = np.random.RandomState(0)

def sharded(a, spec):
    return jax.device_put(jnp.asarray(a), NamedSharding(mesh, spec))

# Megatron-style MLP: wi column-parallel, wo row-parallel over tp.
params = {"wi": sharded(rng.randn(D, H).astype(np.float32), P(None, "tp")),
          "wo": sharded(rng.randn(H, D).astype(np.float32), P("tp", None))}
tx = optax.adam(1e-2)
opt_state = tx.init(params)  # m/v inherit the param shardings
x = jnp.asarray(rng.randn(16, D).astype(np.float32))
y = jnp.asarray(rng.randn(16, D).astype(np.float32))

@jax.jit
def train_step(params, opt_state):
    def loss_fn(p):
        h = jnp.maximum(x @ p["wi"], 0.0)
        return jnp.mean((h @ p["wo"] - y) ** 2)
    loss, g = jax.value_and_grad(loss_fn)(params)
    updates, opt_state = tx.update(g, opt_state, params)
    return optax.apply_updates(params, updates), opt_state, loss

state = {"params": params, "opt": opt_state,
         "step": jnp.zeros((), jnp.int32)}
for _ in range(3):
    p2, o2, loss = train_step(state["params"], state["opt"])
    state = {"params": p2, "opt": o2, "step": state["step"] + 1}

assert checkpoint.has_global_shards(state)
ckdir = "@TMP@/sharded_ck"
checkpoint.save(ckdir, state, step=3)

# Fresh ZERO-valued target with the same shardings: values must come from
# disk, sharding layout from the target leaves.  "Global" here mirrors the
# product's rule: non-addressable AND non-replicated (a replicated scalar
# like Adam's count is host-copyable and round-trips as numpy).
def is_global(v):
    return (isinstance(v, jax.Array) and not v.is_fully_addressable
            and not v.is_fully_replicated)

def zero_like(v):
    if is_global(v):
        return jax.device_put(jnp.zeros(v.shape, v.dtype), v.sharding)
    return np.zeros(np.shape(v), np.asarray(v).dtype)
target = jax.tree.map(zero_like, state)
back = checkpoint.restore(ckdir, step=3, target=target)

# Bit-exact on THIS process's addressable shards, for params AND the Adam
# moments (the sharded optimizer state is the part that tears first).
def assert_shards_equal(a, b):
    if is_global(a):
        for sa, sb in zip(a.addressable_shards, b.addressable_shards):
            np.testing.assert_array_equal(np.asarray(sa.data),
                                          np.asarray(sb.data))
    else:
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
jax.tree.map(assert_shards_equal, state, back)
assert int(back["step"]) == 3

# Training continues from the restored global state.
p3, o3, loss3 = train_step(back["params"], back["opt"])
assert np.isfinite(float(loss3))
print("SHARDED-CKPT-OK", jax.process_index(), flush=True)
"""


@pytest.mark.parametrize("np_procs,devices", [(2, 2)])
def test_sharded_checkpoint_roundtrip(tmp_path, np_procs, devices):
    """A tp-sharded train state (params + Adam moments) saves through the
    coordinated multihost path and restores bit-exact into a zeroed target
    with the same shardings, under bfrun -np 2."""
    out = _run_bfrun(tmp_path, _SHARDED_CKPT_SCRIPT, np_procs, devices)
    assert out.count("SHARDED-CKPT-OK") == np_procs, out


_SHARDED_ELASTIC_SCRIPT = r"""
import sys
sys.path.insert(0, "@REPO@")
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import optax
import bluefog_tpu as bf
from bluefog_tpu.utils.elastic import run_elastic
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

bf.init_distributed()
mesh = Mesh(np.array(jax.devices()), ("tp",))
D, H = 8, 32
rng = np.random.RandomState(0)

def sharded(a, spec):
    return jax.device_put(jnp.asarray(a), NamedSharding(mesh, spec))

params0 = {"wi": sharded(rng.randn(D, H).astype(np.float32), P(None, "tp")),
           "wo": sharded(rng.randn(H, D).astype(np.float32), P("tp", None))}
tx = optax.sgd(0.05)
x = jnp.asarray(rng.randn(16, D).astype(np.float32))
y = jnp.asarray(rng.randn(16, D).astype(np.float32))

@jax.jit
def train_step(params, opt_state):
    def loss_fn(p):
        h = jnp.maximum(x @ p["wi"], 0.0)
        return jnp.mean((h @ p["wo"] - y) ** 2)
    g = jax.grad(loss_fn)(params)
    updates, opt_state = tx.update(g, opt_state, params)
    return optax.apply_updates(params, updates), opt_state

def step_fn(state, step):
    p, o = train_step(state["params"], state["opt"])
    return {"params": p, "opt": o}

def fresh():
    return {"params": params0, "opt": tx.init(params0)}

# Reference: one uninterrupted elastic run (shared dir, coordinated saves).
ref = run_elastic(step_fn, fresh(), ckpt_dir="@TMP@/el_ref", num_steps=8,
                  save_every=2, per_process=False)

# "Crashed" run: first incarnation stops at step 4 (its final save is the
# durable frontier), second incarnation resumes from the SHARED sharded
# checkpoint and replays to 8.
mid = run_elastic(step_fn, fresh(), ckpt_dir="@TMP@/el_crash", num_steps=4,
                  save_every=2, per_process=False)
resumed = run_elastic(step_fn, fresh(), ckpt_dir="@TMP@/el_crash",
                      num_steps=8, save_every=2, per_process=False)

def assert_shards_equal(a, b):
    for sa, sb in zip(a.addressable_shards, b.addressable_shards):
        np.testing.assert_array_equal(np.asarray(sa.data),
                                      np.asarray(sb.data))
jax.tree.map(assert_shards_equal, ref["params"], resumed["params"])
print("SHARDED-ELASTIC-OK", jax.process_index(), flush=True)
"""


def test_sharded_elastic_resume_bit_exact(tmp_path):
    """run_elastic with globally-sharded state: one shared coordinated
    checkpoint dir, synchronous multihost saves, and a crash-resume that
    reproduces the uninterrupted run bit-exactly on every process's
    shards."""
    out = _run_bfrun(tmp_path, _SHARDED_ELASTIC_SCRIPT, 2, 2)
    assert out.count("SHARDED-ELASTIC-OK") == 2, out
