"""Optimizer family tests.

Mirrors the reference's end-to-end convergence strategy
(``test/torch_optimizer_test.py:100-180``): a synthetic linear-regression
problem where each rank sees a different data shard; train and assert the
final global MSE beats a threshold.  Grid over {AWC, ATC} x {empty, allreduce,
neighbor_allreduce, gradient_allreduce} plus dynamic-topology, hierarchical,
local-aggregation and the async window/push-sum optimizers.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import bluefog_tpu as bf
from bluefog_tpu import topology as topo
from bluefog_tpu.optim import CommunicationType

N = 8
DIM = 4
SAMPLES = 16  # per rank


def make_problem(seed=0):
    """Per-rank least squares: y_i = A_i w* + noise; rank-major tensors."""
    rng = np.random.RandomState(seed)
    w_star = rng.randn(DIM, 1)
    A = rng.randn(N, SAMPLES, DIM)
    y = A @ w_star + 0.01 * rng.randn(N, SAMPLES, 1)
    return jnp.asarray(A), jnp.asarray(y), w_star


def global_mse(w, A, y):
    """MSE of each rank's model on the FULL dataset (tests consensus)."""
    pred = np.einsum('msd,ndo->mnso', np.asarray(A), np.asarray(w))
    err = pred - np.asarray(y)[:, None]  # model n on data shard m vs shard m's labels
    return float(np.mean(err ** 2))


def grad_fn(A, y):
    def loss(w_leaf, A_r, y_r):
        return jnp.mean((A_r @ w_leaf - y_r) ** 2)

    g = jax.vmap(jax.grad(loss))

    def compute(params):
        return {"w": g(params["w"], A, y)}
    return jax.jit(compute)


def run_training(opt, A, y, *, steps=120, grads_at=None, seed=1,
                 broadcast_init=False):
    rng = np.random.RandomState(seed)
    # Deliberately diverse inits: consensus must pull the ranks together.
    params = {"w": jnp.asarray(rng.randn(N, DIM, 1) * 2.0)}
    if broadcast_init:
        # Gradient-allreduce never mixes parameters, so ranks must start
        # identical (reference: bf.broadcast_parameters before training).
        params = bf.broadcast_parameters(params, 0)
    state = opt.init(params)
    compute_grads = grad_fn(A, y)
    for _ in range(steps):
        at = grads_at(params) if grads_at is not None else params
        grads = compute_grads(at)
        params, state = opt.step(params, grads, state)
    return params, state


SCENARIOS = [
    ("awc", CommunicationType.neighbor_allreduce),
    ("awc", CommunicationType.allreduce),
    ("awc", CommunicationType.empty),
    ("atc", CommunicationType.neighbor_allreduce),
    ("atc", CommunicationType.allreduce),
    ("gradient_allreduce", CommunicationType.allreduce),
]


@pytest.mark.parametrize("order,comm", SCENARIOS,
                         ids=[f"{o}-{c.name}" for o, c in SCENARIOS])
def test_optimizer_converges(order, comm):
    bf.init(lambda: topo.ExponentialGraph(N))
    A, y, _ = make_problem()
    if order == "gradient_allreduce":
        opt = bf.optim.DistributedGradientAllreduceOptimizer(optax.sgd(0.05))
    else:
        cls = (bf.optim.DistributedAdaptWithCombineOptimizer if order == "awc"
               else bf.optim.DistributedAdaptThenCombineOptimizer)
        opt = cls(optax.sgd(0.05), comm)
    params, _ = run_training(opt, A, y,
                             broadcast_init=order == "gradient_allreduce")
    mse = global_mse(params["w"], A, y)
    # "empty" = local SGD on disjoint shards: no consensus, higher global MSE.
    threshold = 0.5 if comm == CommunicationType.empty else 0.05
    assert mse < threshold, f"{order}/{comm}: global MSE {mse}"
    if comm != CommunicationType.empty:
        w = np.asarray(params["w"])
        spread = np.abs(w - w.mean(axis=0, keepdims=True)).max()
        assert spread < 0.15, f"ranks did not reach consensus: spread {spread}"


def test_neighbor_beats_local():
    """Decentralized averaging must beat no-communication local SGD."""
    bf.init(lambda: topo.ExponentialGraph(N))
    A, y, _ = make_problem()
    nbr = bf.optim.DistributedNeighborAllreduceOptimizer(optax.sgd(0.05))
    loc = bf.optim.DistributedAdaptWithCombineOptimizer(
        optax.sgd(0.05), CommunicationType.empty)
    p_nbr, _ = run_training(nbr, A, y)
    p_loc, _ = run_training(loc, A, y)
    assert global_mse(p_nbr["w"], A, y) < global_mse(p_loc["w"], A, y)


@pytest.mark.parametrize("order", ["awc", "atc"])
@pytest.mark.parametrize("dynamic", [False, True])
def test_fusion_matches_unfused(order, dynamic):
    """Fused single-buffer communication must be numerically identical to
    per-parameter communication (reference fusion oracle tests,
    ``torch_ops_test.py:210-284,962``) — over a multi-leaf pytree so the
    ravel actually concatenates."""
    bf.init(lambda: topo.ExponentialTwoGraph(N))
    rng = np.random.RandomState(3)
    params0 = {"a": jnp.asarray(rng.randn(N, DIM, 1)),
               "b": jnp.asarray(rng.randn(N, 3)),
               "c": jnp.asarray(rng.randn(N, 2, 2))}
    grads = {k: jnp.asarray(rng.randn(*np.asarray(v).shape))
             for k, v in params0.items()}

    outs = {}
    for fusion in (True, False):
        opt = bf.optim.DistributedOptimizer(
            optax.sgd(0.05, momentum=0.9),
            CommunicationType.neighbor_allreduce, order=order,
            use_dynamic_topology=dynamic, fusion=fusion)
        p, s = params0, opt.init(params0)
        for _ in range(3):
            p, s = opt.step(p, grads, s)
        outs[fusion] = p
    for k in params0:
        np.testing.assert_allclose(np.asarray(outs[True][k]),
                                   np.asarray(outs[False][k]),
                                   rtol=1e-6, atol=1e-7)


def _multi_leaf_problem(seed=3):
    rng = np.random.RandomState(seed)
    params = {"a": jnp.asarray(rng.randn(N, DIM, 1)),
              "b": jnp.asarray(rng.randn(N, 3)),
              "c": jnp.asarray(rng.randn(N, 2, 2)),
              "d": jnp.asarray(rng.randn(N, 5))}
    grads = {k: jnp.asarray(rng.randn(*np.asarray(v).shape))
             for k, v in params.items()}
    return params, grads


@pytest.mark.parametrize("order,comm", [
    ("awc", CommunicationType.neighbor_allreduce),
    ("atc", CommunicationType.neighbor_allreduce),
    ("gradient_allreduce", CommunicationType.allreduce),
], ids=["awc", "atc", "gradient_allreduce"])
def test_bucketed_fusion_matches_single_buffer(order, comm):
    """fusion_buckets splits the fused buffer so per-bucket collectives
    pipeline against the other buckets' optimizer math — but it must be
    numerically equivalent to the single-buffer ravel in all three
    execution orders (<= fp32 tolerance; the only difference is float
    summation grouping)."""
    bf.init(lambda: topo.ExponentialTwoGraph(N))
    params0, grads = _multi_leaf_problem()
    outs = {}
    for buckets in (None, 3):
        opt = bf.optim.DistributedOptimizer(
            optax.sgd(0.05, momentum=0.9), comm, order=order,
            fusion_buckets=buckets)
        p, s = params0, opt.init(params0)
        for _ in range(3):
            p, s = opt.step(p, grads, s)
        outs[buckets] = p
    for k in params0:
        np.testing.assert_allclose(np.asarray(outs[None][k]),
                                   np.asarray(outs[3][k]),
                                   rtol=1e-6, atol=1e-6)


def test_bucket_mb_env_cap_matches_single_buffer(monkeypatch):
    """BLUEFOG_TPU_FUSION_BUCKET_MB caps bucket size instead of fixing a
    count; a tiny cap (every leaf its own bucket) must still match the
    single-buffer result."""
    from bluefog_tpu.utils import config
    bf.init(lambda: topo.ExponentialTwoGraph(N))
    params0, grads = _multi_leaf_problem(seed=4)

    def run():
        opt = bf.optim.DistributedNeighborAllreduceOptimizer(optax.sgd(0.05))
        p, s = params0, opt.init(params0)
        for _ in range(2):
            p, s = opt.step(p, grads, s)
        return p
    baseline = run()
    monkeypatch.setenv("BLUEFOG_TPU_FUSION_BUCKET_MB", "0.00001")
    config.reload()
    try:
        capped = run()
    finally:
        monkeypatch.delenv("BLUEFOG_TPU_FUSION_BUCKET_MB")
        config.reload()
    for k in params0:
        np.testing.assert_allclose(np.asarray(capped[k]),
                                   np.asarray(baseline[k]),
                                   rtol=1e-6, atol=1e-6)


def test_bucket_groups_partitioning():
    """Unit contract of the bucket partitioner: contiguous, exhaustive,
    byte-balanced in count mode, size-capped in MB mode."""
    from bluefog_tpu.optim.functional import _bucket_groups
    leaves = [np.zeros(s, np.float32) for s in (100, 50, 200, 10, 40)]
    assert _bucket_groups(leaves, None) == [[0, 1, 2, 3, 4]]
    g2 = _bucket_groups(leaves, 2)
    assert [i for grp in g2 for i in grp] == [0, 1, 2, 3, 4]
    assert len(g2) == 2
    # more buckets than leaves clamps to one leaf per bucket
    g9 = _bucket_groups(leaves, 9)
    assert len(g9) <= 5 and [i for g in g9 for i in g] == [0, 1, 2, 3, 4]
    # fusion_buckets=1 is exactly the legacy single buffer
    assert _bucket_groups(leaves, 1) == [[0, 1, 2, 3, 4]]


def test_fusion_buckets_validation():
    with pytest.raises(ValueError, match="fusion_buckets"):
        bf.optim.DistributedOptimizer(optax.sgd(0.1), fusion_buckets=0)


@pytest.mark.parametrize("factory,kind", [
    (lambda b: bf.optim.DistributedNeighborAllreduceOptimizer(
        b, compression="bf16"), "neighbor"),
    (lambda b: bf.optim.DistributedGradientAllreduceOptimizer(
        b, compression="bf16"), "gradient"),
])
def test_bf16_compression_converges_and_compresses(factory, kind):
    """compression='bf16' halves the wire payload (the reference family's
    fp16 compression role) without breaking convergence, and the lowered
    program really carries bf16 over the collective."""
    bf.init(lambda: topo.ExponentialTwoGraph(N))
    A, y, _ = make_problem()
    opt = factory(optax.sgd(0.05))
    params, state = run_training(opt, A, y,
                                 broadcast_init=(kind == "gradient"))
    assert global_mse(params["w"], A, y) < 0.05

    # the compiled program carries bf16 (this problem is f32 end-to-end, so
    # any bf16 in the lowering comes from the compression casts around the
    # collective); the uncompressed control has none
    grads = {"w": jnp.zeros_like(params["w"])}
    lowered = opt._step_callable(False).lower(params, grads, state).as_text()
    assert "collective_permute" in lowered or "all_reduce" in lowered
    assert "bf16" in lowered
    plain = factory(optax.sgd(0.05))
    plain.compression = "none"
    st0 = plain.init(params)
    assert "bf16" not in plain._step_callable(False).lower(
        params, grads, st0).as_text()


def test_unknown_compression_rejected():
    with pytest.raises(ValueError, match="compression"):
        bf.optim.DistributedOptimizer(optax.sgd(0.1), compression="fp8")


def test_compress_combiner_residual_exact_for_identity():
    """Difference compression: with combine=identity the wrapper is exact
    (a rank's own master weights are never truncated by its own rounds);
    without the residual it quantizes."""
    from bluefog_tpu.optim.functional import compress_combiner
    x = jnp.asarray(np.random.RandomState(0).randn(64).astype(np.float32))
    ident = lambda v, **kw: v  # noqa: E731
    with_res = compress_combiner(ident, "bf16", residual=True)
    np.testing.assert_array_equal(np.asarray(with_res(x)), np.asarray(x))
    no_res = compress_combiner(ident, "bf16", residual=False)
    assert not np.array_equal(np.asarray(no_res(x)), np.asarray(x))
    np.testing.assert_array_equal(
        np.asarray(no_res(x)),
        np.asarray(x.astype(jnp.bfloat16).astype(jnp.float32)))


def test_dynamic_topology_optimizer():
    bf.init(lambda: topo.ExponentialGraph(N))
    A, y, _ = make_problem()
    opt = bf.optim.DistributedNeighborAllreduceOptimizer(
        optax.sgd(0.05), use_dynamic_topology=True)
    params, state = run_training(opt, A, y, steps=150)
    assert int(state.step[0]) == 150
    assert global_mse(params["w"], A, y) < 0.05


def test_adam_base_optimizer():
    """Any optax transformation slots in (the reference hand-codes each
    torch optimizer's math per execution order; optax composes instead)."""
    bf.init(lambda: topo.ExponentialGraph(N))
    A, y, _ = make_problem()
    opt = bf.optim.DistributedAdaptThenCombineOptimizer(
        optax.adam(0.05), CommunicationType.neighbor_allreduce)
    params, _ = run_training(opt, A, y, steps=200)
    assert global_mse(params["w"], A, y) < 0.05


def test_local_aggregation_counts_communication():
    """J=4 must still converge (communicate every 4th step)."""
    bf.init(lambda: topo.ExponentialGraph(N))
    A, y, _ = make_problem()
    opt = bf.optim.DistributedNeighborAllreduceOptimizer(
        optax.sgd(0.05), num_steps_per_communication=4)
    params, _ = run_training(opt, A, y, steps=200)
    assert global_mse(params["w"], A, y) < 0.05


def test_hierarchical_optimizer():
    bf.init(lambda: topo.ExponentialGraph(N), local_size=2)
    A, y, _ = make_problem()
    opt = bf.optim.DistributedHierarchicalNeighborAllreduceOptimizer(
        optax.sgd(0.05))
    params, _ = run_training(opt, A, y, steps=150)
    assert global_mse(params["w"], A, y) < 0.05


def test_step_weight_mutation_no_recompile():
    """Per-step weight kwargs are traced: mutate them every step."""
    bf.init(lambda: topo.RingGraph(N))
    A, y, _ = make_problem()
    opt = bf.optim.DistributedNeighborAllreduceOptimizer(optax.sgd(0.05))
    rng = np.random.RandomState(3)
    params = {"w": jnp.asarray(rng.randn(N, DIM, 1))}
    state = opt.init(params)
    compute_grads = grad_fn(A, y)
    for t in range(60):
        grads = compute_grads(params)
        sw = 0.5 if t % 2 == 0 else 0.4
        nbr_w = (1.0 - sw) / 2.0  # ring: 2 in-neighbors
        w_mat = np.zeros((N, N))
        for r in range(N):
            w_mat[(r - 1) % N, r] = nbr_w
            w_mat[(r + 1) % N, r] = nbr_w
            w_mat[r, r] = sw
        params, state = opt.step(params, grads, state, src_weights=w_mat)
    assert global_mse(params["w"], A, y) < 0.05


def test_explicit_phases_dynamic_optimizer():
    """phases= path: pass a custom phase table (regression: unhashable key)."""
    bf.init(lambda: topo.ExponentialGraph(N))
    A, y, _ = make_problem()
    phases = topo.one_peer_exp2_phases(N)
    opt = bf.optim.DistributedNeighborAllreduceOptimizer(
        optax.sgd(0.05), use_dynamic_topology=True, phases=phases)
    params, _ = run_training(opt, A, y, steps=150)
    assert global_mse(params["w"], A, y) < 0.05


def test_gradient_allreduce_local_aggregation_keeps_replicas_identical():
    """J>1 gradient averaging: accumulate locally, apply the identical
    averaged aggregate on every rank (regression: replica drift)."""
    bf.init(lambda: topo.ExponentialGraph(N))
    A, y, _ = make_problem()
    opt = bf.optim.DistributedGradientAllreduceOptimizer(
        optax.sgd(0.05), num_steps_per_communication=3)
    params, _ = run_training(opt, A, y, steps=150, broadcast_init=True)
    w = np.asarray(params["w"])
    spread = np.abs(w - w[0]).max()
    assert spread < 1e-5, f"replicas drifted: {spread}"
    assert global_mse(params["w"], A, y) < 0.05


def test_weight_override_rejected_for_allreduce():
    """Weight kwargs only make sense for neighbor averaging (regression:
    silently discarded)."""
    bf.init(lambda: topo.ExponentialGraph(N))
    A, y, _ = make_problem()
    opt = bf.optim.DistributedAllreduceOptimizer(optax.sgd(0.05))
    params = {"w": jnp.zeros((N, DIM, 1))}
    state = opt.init(params)
    grads = {"w": jnp.zeros((N, DIM, 1))}
    w_mat = np.eye(N)
    with pytest.raises(ValueError, match="not supported"):
        opt.step(params, grads, state, src_weights=w_mat)


def test_win_put_optimizer_converges():
    bf.init(lambda: topo.ExponentialGraph(N))
    A, y, _ = make_problem()
    opt = bf.optim.DistributedWinPutOptimizer(optax.sgd(0.05))
    params, _ = run_training(opt, A, y, steps=120)
    opt.free()
    assert global_mse(params["w"], A, y) < 0.05


def test_pull_get_optimizer_converges():
    bf.init(lambda: topo.ExponentialGraph(N))
    A, y, _ = make_problem()
    opt = bf.optim.DistributedPullGetOptimizer(optax.sgd(0.05))
    params, _ = run_training(opt, A, y, steps=120)
    opt.free()
    assert global_mse(params["w"], A, y) < 0.05


def test_push_sum_optimizer_converges():
    """Push-sum on a directed ring (column-stochastic only): the de-biased
    iterates must converge to a consensus minimizer."""
    bf.init(lambda: topo.RingGraph(N, connect_style=1))  # directed ring
    A, y, _ = make_problem()
    opt = bf.optim.DistributedPushSumOptimizer(optax.sgd(0.05))
    params, _ = run_training(opt, A, y, steps=150, grads_at=None)
    debiased = opt.debias(params)
    p = opt.associated_p()
    opt.free()
    assert np.all(np.asarray(p) > 0)
    assert global_mse(debiased["w"], A, y) < 0.1
    w = np.asarray(debiased["w"])
    spread = np.abs(w - w.mean(axis=0, keepdims=True)).max()
    assert spread < 0.2, f"push-sum consensus failed: spread {spread}"


def test_donate_matches_undonated():
    """``donate=True`` (buffer aliasing for billion-param configs) must be
    numerically identical to the default step."""
    bf.init(lambda: topo.ExponentialGraph(N))
    A, y, _ = make_problem()
    outs = {}
    for donate in (False, True):
        opt = bf.optim.DistributedNeighborAllreduceOptimizer(
            optax.sgd(0.05), donate=donate)
        params = {"w": jnp.asarray(
            np.random.RandomState(1).randn(N, DIM, 1) * 2.0)}
        state = opt.init(params)
        compute_grads = grad_fn(A, y)
        for _ in range(5):
            grads = compute_grads(params)
            params, state = opt.step(params, grads, state)
        outs[donate] = np.asarray(params["w"]).copy()
    np.testing.assert_array_equal(outs[True], outs[False])


def test_win_put_optimizer_overlap_converges():
    """overlap=True: the put runs behind the caller's compute (one step of
    staleness — the reference's actual async operating mode); convergence
    must survive."""
    bf.init(lambda: topo.ExponentialGraph(N))
    A, y, _ = make_problem()
    opt = bf.optim.DistributedWinPutOptimizer(optax.sgd(0.05), overlap=True)
    params, _ = run_training(opt, A, y, steps=150)
    opt.free()
    assert global_mse(params["w"], A, y) < 0.1


def test_push_sum_optimizer_window_checkpoint_resume():
    """Push-sum optimizer state (incl. window staging + associated-P)
    survives a checkpoint/re-init/restore cycle bit-exactly."""
    bf.init(lambda: topo.RingGraph(N, connect_style=1))
    A, y, _ = make_problem()
    opt = bf.optim.DistributedPushSumOptimizer(optax.sgd(0.05))
    params = {"w": jnp.asarray(
        np.random.RandomState(1).randn(N, DIM, 1).astype(np.float32) * 2.0)}
    state = opt.init(params)
    compute_grads = grad_fn(A, y)
    for _ in range(10):
        params, state = opt.step(params, compute_grads(params), state)
    win_snap = opt.window_state_dict()
    p_mid, s_mid = params, state
    for _ in range(10):
        params, state = opt.step(params, compute_grads(params), state)
    ref = np.asarray(params["w"]).copy()
    p_ref = np.asarray(opt.associated_p()).copy()
    opt.free()
    bf.shutdown()

    bf.init(lambda: topo.RingGraph(N, connect_style=1))
    opt2 = bf.optim.DistributedPushSumOptimizer(optax.sgd(0.05))
    params2 = jax.tree.map(jnp.asarray, p_mid)
    opt2.init(params2)  # recreate windows (zero state)
    opt2.load_window_state_dict(win_snap)
    state2 = s_mid
    for _ in range(10):
        params2, state2 = opt2.step(params2, compute_grads(params2), state2)
    np.testing.assert_array_equal(np.asarray(params2["w"]), ref)
    np.testing.assert_array_equal(np.asarray(opt2.associated_p()), p_ref)
    opt2.free()


def test_window_state_dict_guards():
    """Snapshot/restore misuse fails loudly: no windows, or a snapshot
    taken under a different fuse/prefix layout."""
    bf.init(lambda: topo.ExponentialGraph(N))
    opt = bf.optim.DistributedWinPutOptimizer(optax.sgd(0.05))
    with pytest.raises(RuntimeError, match="no windows exist"):
        opt.window_state_dict()
    params = {"w": jnp.zeros((N, DIM, 1))}
    opt.init(params)
    snap = opt.window_state_dict()
    opt.free()
    with pytest.raises(RuntimeError, match="no windows exist"):
        opt.load_window_state_dict(snap)
    # different layout: per-leaf windows cannot consume a fused snapshot
    opt2 = bf.optim.DistributedWinPutOptimizer(optax.sgd(0.05), fuse=False)
    opt2.init(params)
    with pytest.raises(ValueError, match="fuse= setting or window_prefix"):
        opt2.load_window_state_dict(snap)
    opt2.free()


def test_sparse_compression_converges():
    """compression='sparse:<frac>' on the decentralized family: only 25%
    of entries cross the wire each round (a step-rotating aligned block of
    values + indices over the compiled edge schedule), the residual keeps
    unsent coordinates locally intact; training still reaches the global
    solution."""
    bf.init(lambda: topo.ExponentialGraph(N))
    A, y, _ = make_problem()
    opt = bf.optim.DistributedNeighborAllreduceOptimizer(
        optax.sgd(0.05), compression="sparse:0.25")
    # Each round mixes one block; a full sweep takes ceil(1/frac) rounds.
    params, _ = run_training(opt, A, y, steps=300)
    assert global_mse(params["w"], A, y) < 0.05


def test_sparse_compression_rejects_unsupported_combos():
    """sparse needs a neighbor edge schedule + residual feedback: the
    replica-identical allreduce and the non-converging magnitude-only
    'topk' refuse loudly."""
    bf.init(lambda: topo.ExponentialGraph(N))
    A, y, _ = make_problem()
    params = {"w": jnp.asarray(
        np.random.RandomState(1).randn(N, DIM, 1) * 2.0)}
    opt2 = bf.optim.DistributedAllreduceOptimizer(
        optax.sgd(0.05), compression="sparse:0.25")
    with pytest.raises(ValueError, match="neighbor_allreduce|residual"):
        opt2.step(params, grad_fn(A, y)(params), opt2.init(params))
    opt3 = bf.optim.DistributedNeighborAllreduceOptimizer(
        optax.sgd(0.05), compression="topk:0.25")
    with pytest.raises(ValueError, match="sparse:<frac>"):
        opt3.step(params, grad_fn(A, y)(params), opt3.init(params))


def test_sparse_compression_dynamic_topology_converges():
    """compression='sparse:<frac>' composes with use_dynamic_topology:
    each one-peer Exp2 phase ships only the rotating aligned block over
    its single live edge (k*4 bytes instead of the dense payload), the
    residual keeps unsent coordinates locally intact, and training still
    reaches the global solution with full consensus — the flagship bench
    configuration's compressed mode."""
    bf.init(lambda: topo.ExponentialGraph(N))
    A, y, _ = make_problem()
    opt = bf.optim.DistributedNeighborAllreduceOptimizer(
        optax.sgd(0.05), use_dynamic_topology=True,
        compression="sparse:0.25")
    params, _ = run_training(opt, A, y, steps=400)
    assert global_mse(params["w"], A, y) < 0.05
    w = np.asarray(params["w"])
    spread = np.abs(w - w.mean(axis=0, keepdims=True)).max()
    assert spread < 0.15, f"no consensus under dynamic sparse: {spread}"


def test_sparse_compression_with_local_aggregation_sweeps_all_coords():
    """sparse + num_steps_per_communication > 1: the block must rotate by
    the COMMUNICATION-round index — rotating by the raw step would alias
    (gcd(J*k, size)) and leave whole coordinate blocks unmixed forever."""
    bf.init(lambda: topo.ExponentialGraph(N))
    A, y, _ = make_problem()
    opt = bf.optim.DistributedNeighborAllreduceOptimizer(
        optax.sgd(0.05), compression="sparse:0.25",
        num_steps_per_communication=4)
    params, _ = run_training(opt, A, y, steps=1200)
    assert global_mse(params["w"], A, y) < 0.05
    w = np.asarray(params["w"])
    spread = np.abs(w - w.mean(axis=0, keepdims=True)).max()
    assert spread < 0.1, f"aliased rotation left coords unmixed: {spread}"


def test_sparse_compression_malformed_fraction_rejected():
    bf.init(lambda: topo.ExponentialGraph(N))
    A, y, _ = make_problem()
    params = {"w": jnp.asarray(
        np.random.RandomState(1).randn(N, DIM, 1) * 2.0)}
    for bad in ("sparse:abc", "sparse", "sparse:0", "sparse:1.5"):
        opt = bf.optim.DistributedNeighborAllreduceOptimizer(
            optax.sgd(0.05), compression=bad)
        with pytest.raises(ValueError, match="frac|fraction"):
            opt.step(params, grad_fn(A, y)(params), opt.init(params))


def test_compression_string_validated_even_for_empty_communication():
    """Malformed/rejected compression strings fail fast regardless of the
    communication type — and a valid compression on empty communication
    keeps the identity fast path (no wasted wrap)."""
    from bluefog_tpu.optim import functional as F
    bf.init(lambda: topo.ExponentialGraph(N))
    ident = F.make_combiner(F.CommunicationType.empty, axis_name="bf_rank")
    for bad in ("sparse:abc", "sparse", "topk:0.25", "garbage"):
        with pytest.raises(ValueError):
            F.compress_combiner(ident, bad)
    for ok in ("bf16", "sparse:0.25", "none"):
        out = F.compress_combiner(ident, ok)
        assert getattr(out, "is_identity", False), ok
