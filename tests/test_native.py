"""Native core tests: C++ vs Python oracle parity, timeline output, TCP
window transport loopback."""

import json
import os

import numpy as np
import pytest

from bluefog_tpu import native
from bluefog_tpu import topology as topo
from bluefog_tpu.ops import schedule as S

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native core not built")


@pytest.mark.parametrize("maker", [
    lambda: topo.ExponentialTwoGraph(8),
    lambda: topo.RingGraph(8),
    lambda: topo.StarGraph(8),
    lambda: topo.MeshGrid2DGraph(8),
    lambda: topo.FullyConnectedGraph(8),
    lambda: topo.RingGraph(5, connect_style=2),
    lambda: topo.SymmetricExponentialGraph(12),
])
def test_native_rounds_match_python_oracle(maker):
    w = topo.weight_matrix(maker())
    py = S._rounds_from_matrix_py(w)
    nat = S._rounds_from_matrix_native(w)
    assert nat is not None
    assert len(nat) == len(py)
    for a, b in zip(nat, py):
        assert a.pairs == b.pairs
        np.testing.assert_allclose(a.send_scale, b.send_scale)
        np.testing.assert_allclose(a.recv_mask, b.recv_mask)
        np.testing.assert_array_equal(a.src_of, b.src_of)


def test_native_rounds_random_matrices():
    rng = np.random.RandomState(0)
    for n in (2, 3, 7, 16):
        for _ in range(5):
            w = rng.rand(n, n) * (rng.rand(n, n) < 0.4)
            py = S._rounds_from_matrix_py(w)
            nat = S._rounds_from_matrix_native(w)
            assert [r.pairs for r in nat] == [r.pairs for r in py]
            for a, b in zip(nat, py):
                np.testing.assert_allclose(a.send_scale, b.send_scale)


def test_native_uniform_weights_matches_python():
    import ctypes
    lib = native.lib()
    for maker in (topo.StarGraph, topo.ExponentialGraph):
        w = topo.weight_matrix(maker(8))
        expect = S.uniform_weights(w)
        got = np.ascontiguousarray(w, dtype=np.float64)
        lib.bf_uniform_weights(
            8, got.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        np.testing.assert_allclose(got, expect)


def test_native_timeline_writes_valid_chrome_trace(tmp_path):
    lib = native.lib()
    path = str(tmp_path / "trace.json")
    h = lib.bf_timeline_open(path.encode(), 123)
    assert h
    lib.bf_timeline_event(h, b"alloc", b"NEGOTIATE", b"B", 1000, 0, 7)
    lib.bf_timeline_event(h, b"alloc", b"NEGOTIATE", b"E", 2000, 0, 7)
    lib.bf_timeline_event(h, b"comm", b"COMMUNICATE", b"X", 1500, 300, 7)
    assert lib.bf_timeline_dropped(h) == 0
    lib.bf_timeline_close(h)
    events = json.load(open(path))
    assert [e["ph"] for e in events] == ["B", "E", "X"]
    assert events[2]["dur"] == 300
    assert all(e["pid"] == 123 and e["tid"] == 7 for e in events)


def test_timeline_python_api_uses_native(tmp_path):
    from bluefog_tpu.utils import timeline as tl
    path = str(tmp_path / "t.json")
    assert tl.start_timeline(path)
    with tl.timeline_context("tensor.a", "ALLREDUCE"):
        pass
    tl.timeline_start_activity("tensor.b")
    tl.timeline_end_activity("tensor.b")
    assert tl.stop_timeline()
    events = json.load(open(path))
    names = [(e["cat"], e["name"], e["ph"]) for e in events]
    assert ("tensor.a", "ALLREDUCE", "B") in names
    assert ("tensor.a", "ALLREDUCE", "E") in names
    assert ("tensor.b", "USER", "B") in names


def test_window_transport_loopback():
    """Two endpoints on localhost: puts and accumulates arrive with weights
    and associated-P mass intact, ordered per sender."""
    from bluefog_tpu.ops.transport import (OP_ACCUMULATE, OP_PUT,
                                           WindowTransport)
    received = []
    done = __import__("threading").Event()

    def apply(op, name, src, dst, weight, p_weight, payload):
        received.append((op, name, src, dst, weight, p_weight,
                         np.frombuffer(payload, np.float32).copy()))
        if len(received) == 3:
            done.set()

    server = WindowTransport(apply)
    client = WindowTransport(lambda *a: None)
    try:
        x = np.arange(4, dtype=np.float32)
        client.send("127.0.0.1", server.port, OP_PUT, "w", 1, 0, 0.25, x,
                    p_weight=0.5)
        client.send("127.0.0.1", server.port, OP_ACCUMULATE, "w", 2, 0,
                    0.75, 2 * x, p_weight=0.25)
        client.send("127.0.0.1", server.port, OP_PUT, "very.long/param:name",
                    3, 0, 1.0, np.zeros(0, np.float32))
        assert done.wait(timeout=10), f"only {len(received)} messages arrived"
        op, name, src, dst, w, pw, data = received[0]
        assert (op, name, src, dst, w, pw) == (OP_PUT, "w", 1, 0, 0.25, 0.5)
        np.testing.assert_array_equal(data, x)
        op, name, src, dst, w, pw, data = received[1]
        assert op == OP_ACCUMULATE and w == 0.75
        np.testing.assert_array_equal(data, 2 * x)
        assert received[2][1] == "very.long/param:name"
        assert received[2][6].size == 0
    finally:
        client.stop()
        server.stop()


def test_peer_probe_names_dead_ranks():
    """_probe_missing_ranks reports ranks owned by a process whose transport
    endpoint is gone, and nothing for live peers."""
    import socket

    from bluefog_tpu.ops import window
    from bluefog_tpu.ops.transport import WindowTransport

    live = WindowTransport(lambda *a: None)
    # Bound but never listen()ing: connects are refused, and holding the
    # socket open keeps the port from being rebound by a concurrent process.
    dead_sock = socket.socket()
    dead_sock.bind(("127.0.0.1", 0))
    dead_port = dead_sock.getsockname()[1]
    distrib = window._Distrib(
        live,
        rank_owner={0: 0, 1: 1, 2: 2, 3: 2},
        proc_addr={0: ("127.0.0.1", 1),  # self: never probed
                   1: ("127.0.0.1", live.port),
                   2: ("127.0.0.1", dead_port)},
        my_proc=0)
    saved = window._store.distrib
    window._store.distrib = distrib
    try:
        assert window._probe_missing_ranks(timeout=2.0) == [2, 3]
    finally:
        window._store.distrib = saved
        dead_sock.close()
        live.stop()


def test_window_transport_large_payload():
    """Payload bigger than the initial drain buffer (forces regrow)."""
    from bluefog_tpu.ops.transport import OP_PUT, WindowTransport
    got = []
    done = __import__("threading").Event()

    def apply(op, name, src, dst, weight, p_weight, payload):
        # payload is a zero-copy view into the recv buffer, valid only
        # for the duration of this call — snapshot before retaining.
        got.append(np.frombuffer(payload, np.float32).copy())
        done.set()

    server = WindowTransport(apply)
    try:
        x = np.random.RandomState(0).randn(3 << 20).astype(np.float32)  # 12MB
        server.send("127.0.0.1", server.port, OP_PUT, "big", 0, 0, 1.0, x)
        assert done.wait(timeout=30)
        np.testing.assert_array_equal(got[0], x)
    finally:
        server.stop()


def test_timeline_autostart_per_rank_and_parses(tmp_path, monkeypatch):
    """BLUEFOG_TIMELINE autostart writes <prefix><rank>.json (reference
    operations.cc:450-459) and the emitted JSON parses to matched B/E pairs
    around real ops (reference test/timeline_test.py:54-140)."""
    import json

    import numpy as np

    import bluefog_tpu as bf
    from bluefog_tpu import topology as topo
    from bluefog_tpu.utils import timeline as tl

    prefix = str(tmp_path / "tl_")
    monkeypatch.setenv("BLUEFOG_TIMELINE", prefix)
    monkeypatch.setenv("BFTPU_PROCESS_ID", "3")
    tl.stop_timeline()
    try:
        bf.init(lambda: topo.RingGraph(8))
        x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        with bf.timeline_context("consensus", "NEIGHBOR_ALLREDUCE"):
            bf.neighbor_allreduce(x)
        bf.timeline_start_activity("step", "ENQUEUE")
        bf.timeline_end_activity("step", "ENQUEUE")
        assert tl.stop_timeline()
        path = tmp_path / "tl_3.json"
        assert path.exists(), list(tmp_path.iterdir())
        events = json.load(open(path))
        by_cat = {}
        for ev in events:
            by_cat.setdefault((ev["cat"], ev["name"]), []).append(ev["ph"])
        assert by_cat[("consensus", "NEIGHBOR_ALLREDUCE")] == ["B", "E"]
        assert by_cat[("step", "ENQUEUE")] == ["B", "E"]
        # ops emit automatic phase events (reference mpi_controller.cc:540)
        assert by_cat.get(("neighbor_allreduce", "ENQUEUE")), by_cat.keys()
        assert by_cat.get(("synchronize", "COMMUNICATE")), by_cat.keys()
    finally:
        tl.stop_timeline()


def test_native_timeline_concurrent_producers(tmp_path):
    """Hammer the native ring from many threads: every event must land
    exactly once (the MPSC claim/publish path)."""
    import json
    import threading

    from bluefog_tpu import native
    if not native.available():
        pytest.skip("native lib unavailable")
    lib = native.lib()
    path = str(tmp_path / "mpsc.json")
    h = lib.bf_timeline_open(path.encode(), 1)
    n_threads, per_thread = 8, 2000

    def pump(t):
        for i in range(per_thread):
            lib.bf_timeline_event(h, f"t{t}".encode(), b"CAT", b"X",
                                  i, 1, t)

    threads = [threading.Thread(target=pump, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dropped = lib.bf_timeline_dropped(h)
    lib.bf_timeline_close(h)
    events = json.load(open(path))
    counts = {}
    for ev in events:
        counts[ev["name"]] = counts.get(ev["name"], 0) + 1
    total = sum(counts.values()) + dropped
    assert total == n_threads * per_thread, (counts, dropped)
    # no torn/mixed records: every event kept its thread's name/tid pairing
    for ev in events:
        assert ev["name"] == f"t{ev['tid']}", ev


def test_timeline_per_edge_window_spans(tmp_path, monkeypatch):
    """The window family's host-side path emits PER-EDGE COMMUNICATE spans
    — put/accumulate/get per (src, dst) — the granularity one fused XLA
    program cannot show (VERDICT r3 next-round #8)."""
    import json

    import numpy as np

    import bluefog_tpu as bf
    from bluefog_tpu import topology as topo
    from bluefog_tpu.utils import timeline as tl

    prefix = str(tmp_path / "edge_")
    monkeypatch.setenv("BLUEFOG_TIMELINE", prefix)
    monkeypatch.delenv("BFTPU_PROCESS_ID", raising=False)
    tl.stop_timeline()
    try:
        bf.init(lambda: topo.RingGraph(8))
        x = np.ones((8, 3), np.float32)
        bf.win_create(x, "w", zero_init=True)
        bf.win_put(x, "w")
        bf.win_accumulate(x, "w")
        bf.win_get("w")
        bf.win_update("w")
        bf.win_free("w")
        assert tl.stop_timeline()
        events = json.load(open(str(tmp_path / "edge_0.json")))
        cats = {}
        for ev in events:
            cats.setdefault(ev["cat"], []).append(ev["ph"])
        # Every ring edge of every op family gets its own matched span.
        for kind in ("win_put", "win_accumulate", "win_get"):
            for dst in range(8):
                for src in ((dst - 1) % 8, (dst + 1) % 8):
                    key = f"{kind}.w.{src}->{dst}"
                    assert cats.get(key) == ["B", "E"], (key, cats.keys())
        # The op-level spans remain (edge spans nest inside them).
        assert "win_put.w" in cats and "win_update.w" in cats
    finally:
        tl.stop_timeline()


# ---------------------------------------------------------------------------
# Stale-library detection (native/__init__.py)
# ---------------------------------------------------------------------------

def test_stale_sources_detects_newer_sources(tmp_path):
    """A src/*.cc or *.h newer than the built library is reported; a fresh
    tree is not (pure mtime logic, exercised on a synthetic tree)."""
    lib = tmp_path / "libfake.so"
    src = tmp_path / "src"
    src.mkdir()
    (src / "winsvc.cc").write_text("// a")
    (src / "core.h").write_text("// b")
    (src / "README").write_text("not a source")
    lib.write_text("so")
    old, new = 1_000_000, 2_000_000
    os.utime(lib, (new, new))
    os.utime(src / "winsvc.cc", (old, old))
    os.utime(src / "core.h", (old, old))
    assert native._stale_sources(str(lib), str(src)) == []
    os.utime(src / "winsvc.cc", (new + 10, new + 10))
    assert native._stale_sources(str(lib), str(src)) == ["winsvc.cc"]
    os.utime(src / "core.h", (new + 20, new + 20))
    assert native._stale_sources(str(lib), str(src)) == ["core.h",
                                                         "winsvc.cc"]
    # Missing artifacts are "not stale" (nothing to mis-trust yet).
    assert native._stale_sources(str(tmp_path / "absent.so"),
                                 str(src)) == []


def test_win_native_capability_reports():
    """A freshly-built core exposes the window hot-path symbols.  A stale
    or symbol-old build (old .so, no toolchain to refresh it) is a
    SUPPORTED degraded mode — the transport disarms its fast path and the
    Python fallback serves — so it skips here rather than failing."""
    assert native.available()
    if native.is_stale() or not native.has_win_native():
        pytest.skip("stale/symbol-old native build: supported degraded "
                    "mode (Python fallback active)")
    lib = native.lib()
    for sym in ("bf_wintx_start", "bf_wintx_send", "bf_wintx_flush",
                "bf_wintx_drop_peer", "bf_winsvc_drain",
                "bf_winsvc_win_set", "bf_winsvc_rx_stats"):
        assert hasattr(lib, sym), sym
