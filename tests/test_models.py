"""Model zoo smoke tests: shapes, dtypes, jit-ability."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bluefog_tpu import models


def test_lenet_forward():
    m = models.LeNet5()
    x = jnp.zeros((4, 28, 28, 1))
    params = m.init(jax.random.PRNGKey(0), x)
    out = jax.jit(lambda p, x: m.apply(p, x))(params, x)
    assert out.shape == (4, 10)
    assert out.dtype == jnp.float32


def test_mlp_and_logreg_forward():
    x = jnp.zeros((4, 28, 28, 1))
    m = models.MLP()
    out = m.apply(m.init(jax.random.PRNGKey(0), x), x)
    assert out.shape == (4, 10)
    lr = models.LogisticRegression(num_classes=3)
    x2 = jnp.zeros((5, 7))
    assert lr.apply(lr.init(jax.random.PRNGKey(0), x2), x2).shape == (5, 3)


@pytest.mark.slow
def test_resnet18_forward_and_bn_state():
    m = models.ResNet18(num_classes=10, dtype=jnp.float32)
    x = jnp.zeros((2, 32, 32, 3))
    variables = m.init(jax.random.PRNGKey(0), x)
    assert "batch_stats" in variables
    out, new_state = m.apply(variables, x, train=True,
                             mutable=["batch_stats"])
    assert out.shape == (2, 10)
    out_eval = m.apply(variables, x, train=False)
    assert out_eval.shape == (2, 10)


@pytest.mark.slow
def test_resnet50_param_count():
    """ResNet-50 must be the real thing: ~25.6M parameters."""
    m = models.ResNet50(num_classes=1000, dtype=jnp.float32)
    x = jnp.zeros((1, 64, 64, 3))
    variables = m.init(jax.random.PRNGKey(0), x)
    n_params = sum(np.prod(p.shape) for p in
                   jax.tree_util.tree_leaves(variables["params"]))
    assert 25.4e6 < n_params < 25.8e6, f"got {n_params/1e6:.2f}M params"


def test_transformer_forward():
    cfg = models.TransformerConfig(vocab_size=100, num_layers=2, num_heads=2,
                                   embed_dim=32, max_seq_len=16,
                                   dtype=jnp.float32)
    m = models.TransformerLM(cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    params = m.init(jax.random.PRNGKey(0), tokens)
    logits = m.apply(params, tokens)
    assert logits.shape == (2, 16, 100)


@pytest.mark.slow
def test_transformer_gqa_and_mqa():
    """Grouped-query attention: fewer K/V projection params, same output
    shape, finite grads; flash kernel agrees with dense on GQA shapes."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from bluefog_tpu.models import TransformerLM, TransformerConfig
    from bluefog_tpu.ops.flash_attention import flash_attention_impl

    kw = dict(vocab_size=64, num_layers=2, num_heads=4, embed_dim=64,
              max_seq_len=32, dtype=jnp.float32)
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 32)))

    def count(m):
        v = m.init(jax.random.PRNGKey(0), tokens)
        return v, sum(int(np.prod(p.shape))
                      for p in jax.tree_util.tree_leaves(v["params"]))

    mha, n_mha = count(TransformerLM(TransformerConfig(**kw)))
    gqa_model = TransformerLM(TransformerConfig(num_kv_heads=2, **kw))
    gqa, n_gqa = count(gqa_model)
    mqa, n_mqa = count(TransformerLM(TransformerConfig(num_kv_heads=1, **kw)))
    assert n_mqa < n_gqa < n_mha  # K/V projections shrink with kv heads

    logits = gqa_model.apply(gqa, tokens)
    assert logits.shape == (2, 32, 64)

    def loss(p):
        return jnp.mean(gqa_model.apply(p, tokens) ** 2)
    grads = jax.grad(loss)(gqa)
    assert all(np.all(np.isfinite(g)) for g in
               jax.tree_util.tree_leaves(grads))

    # same params, flash vs dense attention on the grouped-head shapes
    flash_model = TransformerLM(TransformerConfig(num_kv_heads=2, **kw),
                                attn_impl=flash_attention_impl(block_q=16,
                                                               block_k=16))
    np.testing.assert_allclose(np.asarray(flash_model.apply(gqa, tokens)),
                               np.asarray(logits), rtol=2e-3, atol=2e-3)


def test_transformer_rope_relative_shift_invariance():
    """RoPE attends by relative position: shifting every position id by a
    constant must leave the logits unchanged (learned-wpe would not)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from bluefog_tpu.models import TransformerLM, TransformerConfig
    from bluefog_tpu.models.transformer import apply_rope

    # unit: position 0 is the identity rotation
    x = jnp.asarray(np.random.RandomState(0).randn(1, 3, 2, 8), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(apply_rope(x, jnp.zeros((1, 3)))), np.asarray(x),
        rtol=1e-6)

    cfg = TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                            embed_dim=64, max_seq_len=512,
                            pos_encoding="rope", dtype=jnp.float32)
    m = TransformerLM(cfg)
    tokens = jnp.asarray(np.random.RandomState(1).randint(0, 64, (2, 16)))
    params = m.init(jax.random.PRNGKey(0), tokens)
    assert not any("wpe" in "/".join(map(str, p)) for p, _ in
                   jax.tree_util.tree_flatten_with_path(params)[0])
    base = m.apply(params, tokens,
                   positions=jnp.arange(16)[None, :])
    shifted = m.apply(params, tokens,
                      positions=jnp.arange(16)[None, :] + 100)
    np.testing.assert_allclose(np.asarray(shifted), np.asarray(base),
                               rtol=1e-4, atol=1e-4)


def test_transformer_rope_flash_matches_dense():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from bluefog_tpu.models import TransformerLM, TransformerConfig
    from bluefog_tpu.ops.flash_attention import flash_attention_impl

    kw = dict(vocab_size=64, num_layers=2, num_heads=4, embed_dim=64,
              max_seq_len=64, pos_encoding="rope", num_kv_heads=2,
              dtype=jnp.float32)
    tokens = jnp.asarray(np.random.RandomState(2).randint(0, 64, (2, 32)))
    dense = TransformerLM(TransformerConfig(**kw))
    params = dense.init(jax.random.PRNGKey(0), tokens)
    flash = TransformerLM(TransformerConfig(**kw),
                          attn_impl=flash_attention_impl(block_q=16,
                                                         block_k=16))
    np.testing.assert_allclose(np.asarray(flash.apply(params, tokens)),
                               np.asarray(dense.apply(params, tokens)),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_transformer_swiglu_trains():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from bluefog_tpu.models import TransformerLM, TransformerConfig

    cfg = TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                            embed_dim=32, max_seq_len=16, mlp="swiglu",
                            dtype=jnp.float32)
    m = TransformerLM(cfg)
    tokens = jnp.asarray(np.random.RandomState(3).randint(0, 64, (4, 16)))
    params = m.init(jax.random.PRNGKey(0), tokens)
    names = {"/".join(str(getattr(k, "key", k)) for k in p)
             for p, _ in jax.tree_util.tree_flatten_with_path(params)[0]}
    assert "params/block_0/gate/kernel" in names

    opt = optax.adam(1e-3)

    def loss(p):
        logits = m.apply(p, tokens)
        tgt = jnp.roll(tokens, -1, axis=1)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tgt).mean()

    state = opt.init(params)
    l0 = float(loss(params))
    for _ in range(30):
        g = jax.grad(loss)(params)
        upd, state = opt.update(g, state, params)
        params = optax.apply_updates(params, upd)
    assert float(loss(params)) < l0


@pytest.mark.slow
@pytest.mark.parametrize("variant", ["mha", "gqa_rope_swiglu"])
def test_transformer_kv_cache_decode_matches_forward(variant):
    """Teacher-forced single-token decoding through the KV cache must
    reproduce the full training forward's logits position by position."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from bluefog_tpu.models import TransformerLM, TransformerConfig
    from bluefog_tpu.models.transformer import init_cache

    kw = dict(vocab_size=64, num_layers=2, num_heads=4, embed_dim=32,
              max_seq_len=16, dtype=jnp.float32)
    if variant == "gqa_rope_swiglu":
        kw.update(num_kv_heads=2, pos_encoding="rope", mlp="swiglu")
    m = TransformerLM(TransformerConfig(**kw))
    tokens = jnp.asarray(np.random.RandomState(5).randint(0, 64, (2, 10)))
    params = m.init(jax.random.PRNGKey(0), tokens)
    full = m.apply(params, tokens)  # (2, 10, 64)

    cache = init_cache(m.cfg, 2, 10)
    # GQA cache is kv_h-headed: h/kv_h smaller than num_heads
    kv_h = m.cfg.num_kv_heads or m.cfg.num_heads
    assert cache[0][0].shape == (2, 10, kv_h, 32 // 4)
    got = []
    for t in range(10):
        logits, cache = m.apply(
            params, tokens[:, t:t + 1],
            positions=jnp.broadcast_to(jnp.asarray(t), (2, 1)), cache=cache)
        got.append(logits[:, 0])
    np.testing.assert_allclose(np.asarray(jnp.stack(got, 1)),
                               np.asarray(full), rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_transformer_generate_greedy_and_sampled():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from bluefog_tpu.models import TransformerLM, TransformerConfig
    from bluefog_tpu.models.transformer import generate

    cfg = TransformerConfig(vocab_size=32, num_layers=2, num_heads=2,
                            embed_dim=32, max_seq_len=24,
                            dtype=jnp.float32)
    m = TransformerLM(cfg)
    prompt = jnp.asarray(np.random.RandomState(6).randint(0, 32, (2, 5)))
    params = m.init(jax.random.PRNGKey(0), prompt)

    out = generate(m, params, prompt, 6)
    assert out.shape == (2, 6) and out.dtype == prompt.dtype
    # greedy decoding is deterministic
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(generate(m, params, prompt, 6)))
    # greedy first token == argmax of the forward's last-prompt logits
    full = m.apply(params, prompt)
    np.testing.assert_array_equal(np.asarray(out[:, 0]),
                                  np.asarray(jnp.argmax(full[:, -1], -1)))
    sampled = generate(m, params, prompt, 6, temperature=1.0,
                       rng=jax.random.PRNGKey(1))
    assert sampled.shape == (2, 6)
    assert generate(m, params, prompt, 1).shape == (2, 1)
    with pytest.raises(ValueError, match="needs rng"):
        generate(m, params, prompt, 2, temperature=0.5)
    with pytest.raises(ValueError, match="exceeds"):
        generate(m, params, prompt, 100)
    with pytest.raises(ValueError, match="max_new_tokens"):
        generate(m, params, prompt, 0)
    # decode-contract violations are loud, not silently corrupting
    from bluefog_tpu.models.transformer import init_cache
    cache = init_cache(cfg, 2, 8)
    with pytest.raises(ValueError, match="ONE token"):
        m.apply(params, prompt[:, :3],
                positions=jnp.zeros((2, 3), jnp.int32), cache=cache)
    with pytest.raises(ValueError, match="explicit positions"):
        m.apply(params, prompt[:, :1], cache=cache)


def test_transformer_gqa_validates_divisibility():
    from bluefog_tpu.models import TransformerConfig
    with pytest.raises(ValueError, match="divisible"):
        TransformerConfig(num_heads=4, num_kv_heads=3)
    with pytest.raises(ValueError, match="even head dim"):
        TransformerConfig(embed_dim=90, num_heads=6, pos_encoding="rope")
    with pytest.raises(ValueError, match="contradictory"):
        TransformerConfig(mlp="swiglu", num_experts=4)
    with pytest.raises(ValueError, match="dots:<int>"):
        TransformerConfig(remat=True, remat_policy="dots:abc")
    with pytest.raises(ValueError, match="not in"):
        TransformerConfig(remat=True, remat_policy="mixed")
    TransformerConfig(remat=True, remat_policy="dots:8")  # valid mixed


@pytest.mark.slow
@pytest.mark.parametrize("policy", ["full", "dots", "dots:1"])
def test_transformer_remat_matches_plain(policy):
    """cfg.remat=True (jax.checkpoint per block, either policy) must not
    change outputs or gradients — only the backward's memory/recompute
    schedule."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from bluefog_tpu.models import TransformerLM, TransformerConfig

    kw = dict(vocab_size=64, num_layers=2, num_heads=4, embed_dim=32,
              max_seq_len=16, dtype=jnp.float32)
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 16)))
    plain = TransformerLM(TransformerConfig(**kw))
    remat = TransformerLM(TransformerConfig(remat=True, remat_policy=policy,
                                            **kw))
    params = plain.init(jax.random.PRNGKey(0), tokens)

    out_p = plain.apply(params, tokens)
    out_r = remat.apply(params, tokens)
    np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_p),
                               rtol=1e-6, atol=1e-6)

    loss = lambda m: lambda p: jnp.sum(m.apply(p, tokens) ** 2)
    g_p = jax.grad(loss(plain))(params)
    g_r = jax.grad(loss(remat))(params)
    for a, b in zip(jax.tree_util.tree_leaves(g_p),
                    jax.tree_util.tree_leaves(g_r)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-5, atol=1e-5)


def test_chunked_loss_matches_dense():
    """chunked_softmax_cross_entropy == optax dense CE in value and grad,
    through the model's return_hidden path."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from bluefog_tpu.models import TransformerLM, TransformerConfig
    from bluefog_tpu.ops.chunked_loss import chunked_softmax_cross_entropy

    cfg = TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                            embed_dim=32, max_seq_len=16, dtype=jnp.float32)
    model = TransformerLM(cfg)
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 16)))
    params = model.init(jax.random.PRNGKey(0), tokens)
    tgt = jnp.roll(tokens, -1, axis=1)

    def dense_loss(p):
        return optax.softmax_cross_entropy_with_integer_labels(
            model.apply(p, tokens), tgt).mean()

    def chunked_loss(p):
        h = model.apply(p, tokens, return_hidden=True)
        return chunked_softmax_cross_entropy(
            h, p["params"]["lm_head"]["kernel"], tgt, chunk=4)

    np.testing.assert_allclose(float(chunked_loss(params)),
                               float(dense_loss(params)), rtol=1e-5)
    g_d = jax.grad(dense_loss)(params)
    g_c = jax.grad(chunked_loss)(params)
    for a, b in zip(jax.tree_util.tree_leaves(g_d),
                    jax.tree_util.tree_leaves(g_c)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-5)


def test_chunked_loss_uneven_chunk_fits_down():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from bluefog_tpu.ops.chunked_loss import chunked_softmax_cross_entropy
    h = jnp.asarray(np.random.RandomState(0).randn(1, 12, 8), jnp.float32)
    W = jnp.asarray(np.random.RandomState(1).randn(8, 20), jnp.float32)
    t = jnp.asarray(np.random.RandomState(2).randint(0, 20, (1, 12)))
    # chunk=8 does not divide 12 -> fits down to 6 (largest divisor)
    out = chunked_softmax_cross_entropy(h, W, t, chunk=8)
    ref = chunked_softmax_cross_entropy(h, W, t, chunk=12)
    np.testing.assert_allclose(float(out), float(ref), rtol=1e-6)


@pytest.mark.slow
def test_switch_moe_transformer_trains():
    """num_experts>0 swaps each block's MLP for a switch MoE; the model
    trains (loss falls) and router + expert weights all receive grads."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from bluefog_tpu.models import TransformerLM, TransformerConfig

    cfg = TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                            embed_dim=32, max_seq_len=16, dtype=jnp.float32,
                            num_experts=4)
    model = TransformerLM(cfg)
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 64, (4, 16)))
    params = model.init(jax.random.PRNGKey(0), tokens)
    names = [str(p) for p, _ in
             jax.tree_util.tree_flatten_with_path(params)[0]]
    assert any("experts_up" in n for n in names), names
    assert any("router" in n for n in names), names

    def loss(p):
        logits = model.apply(p, tokens)
        tgt = jnp.roll(tokens, -1, axis=1)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tgt).mean()

    opt = optax.adam(1e-2)
    state = opt.init(params)
    l0 = float(loss(params))

    @jax.jit
    def train_step(p, s):
        g = jax.grad(loss)(p)
        upd, s = opt.update(g, s, p)
        return optax.apply_updates(p, upd), s
    for _ in range(30):
        params, state = train_step(params, state)
    l1 = float(loss(params))
    assert l1 < l0 * 0.7, (l0, l1)
    g = jax.grad(loss)(params)
    flat = jax.tree_util.tree_flatten_with_path(g)[0]
    for p, leaf in flat:
        if "experts" in str(p) or "router" in str(p):
            assert float(jnp.abs(leaf).max()) > 0, p
    # the Switch load-balance aux loss is sown per MoE layer
    _, inter = model.apply(params, tokens, mutable=["intermediates"])
    aux = [v for k, v in
           jax.tree_util.tree_flatten_with_path(inter)[0]
           if "moe_aux_loss" in str(k)]
    assert len(aux) == cfg.num_layers, inter
    assert all(np.isfinite(float(a)) and float(a) >= 1.0 - 1e-6
               for a in aux), aux  # >= 1 by Cauchy-Schwarz, = 1 if balanced


def test_switch_moe_expert_parallel_sharding_matches():
    """Expert weights sharded P('ep') under GSPMD: same outputs as the
    unsharded model."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from bluefog_tpu.models import TransformerLM, TransformerConfig
    from bluefog_tpu.parallel.tensor_parallel import (tp_param_specs,
                                                      tp_shard_params)

    cfg = TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                            embed_dim=32, max_seq_len=16, dtype=jnp.float32,
                            num_experts=4)
    model = TransformerLM(cfg)
    tokens = jnp.asarray(np.random.RandomState(1).randint(0, 64, (4, 16)))
    params = model.init(jax.random.PRNGKey(0), tokens)
    ref = model.apply(params, tokens)

    # TP and EP composed on one mesh: attention/up/down shard over tp,
    # stacked expert weights over ep.
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4), ("tp", "ep"))
    specs = tp_param_specs(params, axis="tp", ep_axis="ep")
    flat = jax.tree_util.tree_flatten_with_path(specs)[0]
    assert sum(1 for _, s in flat if s == P("ep", None, None)) == 4  # 2x2
    p_sh = tp_shard_params(params, mesh, axis="tp", ep_axis="ep")
    t_sh = jax.device_put(tokens, NamedSharding(mesh, P()))
    out = jax.jit(model.apply)(p_sh, t_sh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_switch_moe_ragged_group_padding():
    """T not divisible by router_group_size: tokens pad to whole groups and
    the output slices back — no silent group-size collapse."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from bluefog_tpu.models import TransformerLM, TransformerConfig
    cfg = TransformerConfig(vocab_size=32, num_layers=1, num_heads=2,
                            embed_dim=16, max_seq_len=13, dtype=jnp.float32,
                            num_experts=2, router_group_size=5)
    model = TransformerLM(cfg)
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 32, (3, 13)))
    params = model.init(jax.random.PRNGKey(0), tokens)  # T=39, g=5 -> pad 1
    out = model.apply(params, tokens)
    assert out.shape == (3, 13, 32)
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.slow
def test_vgg16_forward_and_grad():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from bluefog_tpu.models import VGG16
    model = VGG16(num_classes=10, hidden=64, dtype=jnp.float32)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 32, 32, 3),
                    jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)
    out = model.apply(params, x)
    assert out.shape == (2, 10)
    g = jax.grad(lambda p: jnp.sum(model.apply(p, x) ** 2))(params)
    assert all(np.isfinite(np.asarray(l)).all()
               for l in jax.tree_util.tree_leaves(g))


def test_vit_forward_and_grad():
    """ViT: patchify + [CLS] + bidirectional encoder blocks; logits shape,
    gradient flow to every parameter group."""
    m = models.ViT(num_classes=10, image_size=32, patch_size=8,
                   embed_dim=64, num_layers=2, num_heads=4,
                   dtype=jnp.float32)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 32, 32, 3),
                    jnp.float32)
    params = m.init(jax.random.PRNGKey(0), x)
    out = jax.jit(lambda p, x: m.apply(p, x))(params, x)
    assert out.shape == (2, 10)
    assert out.dtype == jnp.float32

    def loss(p):
        return jnp.sum(m.apply(p, x) ** 2)
    g = jax.grad(loss)(params)
    for path, leaf in jax.tree_util.tree_flatten_with_path(g)[0]:
        assert float(jnp.abs(leaf).sum()) > 0, \
            f"no gradient reached {jax.tree_util.keystr(path)}"


def test_vit_attention_is_bidirectional():
    """Information must flow from LATER patches into the [CLS] token's
    logits beyond what a causal mask would allow: perturbing the LAST
    patch changes the [CLS]-derived output (under a causal mask the CLS
    position, index 0, could never see it)."""
    m = models.ViT(num_classes=4, image_size=16, patch_size=8,
                   embed_dim=32, num_layers=1, num_heads=2,
                   dtype=jnp.float32)
    x = jnp.asarray(np.random.RandomState(1).randn(1, 16, 16, 3),
                    jnp.float32)
    params = m.init(jax.random.PRNGKey(0), x)
    base = np.asarray(m.apply(params, x))
    x2 = x.at[:, 8:, 8:, :].add(1.0)  # last patch only
    pert = np.asarray(m.apply(params, x2))
    assert np.abs(pert - base).max() > 1e-4, \
        "CLS logits blind to later patches — attention is causal"


def test_vit_validates_patch_divisibility():
    m = models.ViT(image_size=30, patch_size=16)
    with pytest.raises(ValueError, match="not divisible"):
        m.init(jax.random.PRNGKey(0), jnp.zeros((1, 30, 30, 3)))


def test_kv_cache_rejects_bidirectional_config():
    """causal=False (encoder mode) must not silently decode causally."""
    from bluefog_tpu.models import transformer as T
    cfg = models.TransformerConfig(vocab_size=32, num_layers=1, num_heads=2,
                                   embed_dim=16, max_seq_len=8,
                                   dtype=jnp.float32, causal=False)
    m = models.TransformerLM(cfg)
    tokens = jnp.zeros((1, 8), jnp.int32)
    params = m.init(jax.random.PRNGKey(0), tokens)
    cache = T.init_cache(cfg, batch=1, max_len=8)
    with pytest.raises(ValueError, match="causal=True"):
        m.apply(params, jnp.zeros((1, 1), jnp.int32),
                positions=jnp.zeros((1, 1), jnp.int32), cache=cache)
