"""Gang join/bootstrap subsystem: directory CRDT semantics, persistence,
placement-aware admission, launcher wiring, owned-ranks invalidation for
grown gangs, telemetry surfaces.

The full join-a-rank-mid-training path runs as the `make chaos-smoke`
join/kill-rank-0 legs (and the slow-marked wrappers at the bottom); here
the pieces are exercised hermetically."""

import json
import os

import numpy as np
import pytest

from bluefog_tpu.ops import gang
from bluefog_tpu.utils import config, telemetry


@pytest.fixture(autouse=True)
def _clean():
    yield
    gang.install(None)
    telemetry.reset()
    config.reload()


# ---------------------------------------------------------------------------
# GangDirectory: CRDT merge + persistence
# ---------------------------------------------------------------------------

def _dir(n=4, eps=None, epoch=0, active=(0, 1, 2, 3), owner=None):
    eps = eps if eps is not None else {p: f"h:{p + 1}" for p in range(4)}
    owner = owner if owner is not None else {r: r for r in range(n)}
    return gang.GangDirectory(n, eps, epoch=epoch, active=active,
                              rank_owner=owner)


def test_directory_round_trips_through_dict():
    d = _dir(epoch=3, active=(0, 2))
    d2 = gang.GangDirectory.from_dict(d.to_dict())
    assert d2.to_dict() == d.to_dict()
    assert d2.rank_owner == d.rank_owner and d2.epoch == 3


def test_directory_merge_unions_endpoints_and_adopts_higher_epoch():
    a = _dir(eps={0: "h:1", 1: "h:2"}, epoch=1, active=(0, 1))
    b = _dir(eps={1: "h:2", 4: "h:9"}, epoch=2, active=(0, 1, 4),
             owner={0: 0, 1: 1, 2: 4, 3: 3})
    assert a.merge(b) is True
    assert a.endpoints == {0: "h:1", 1: "h:2", 4: "h:9"}
    assert a.epoch == 2 and a.active == (0, 1, 4)
    assert a.rank_owner[2] == 4
    # Merging an older replica changes nothing (anti-entropy is monotone).
    old = _dir(eps={0: "h:1"}, epoch=0, active=(0, 1, 2, 3))
    assert a.merge(old) is False
    assert a.epoch == 2


def test_directory_merge_endpoint_conflict_is_deterministic(caplog):
    import logging

    from bluefog_tpu.utils.logging import get_logger
    a = _dir(eps={0: "h:5"})
    b = _dir(eps={0: "h:2"})
    log = get_logger()
    log.addHandler(caplog.handler)  # the package logger does not propagate
    try:
        with caplog.at_level(logging.WARNING, logger="bluefog_tpu"):
            a.merge(b)
    finally:
        log.removeHandler(caplog.handler)
    assert a.endpoints[0] == "h:2"  # lexicographic min, both sides agree
    b2 = _dir(eps={0: "h:2"})
    b2.merge(_dir(eps={0: "h:5"}))
    assert b2.endpoints[0] == "h:2"
    assert any("conflicting endpoints" in r.message for r in caplog.records)


def test_directory_vacant_and_live_endpoints():
    d = _dir(epoch=1, active=(0, 1, 3))
    assert d.vacant_ranks() == [2]
    assert d.live_endpoints() == [("h", 1), ("h", 2), ("h", 4)]


def test_directory_persist_load_and_load_any(tmp_path):
    prefix = str(tmp_path / "gang")
    a = _dir(epoch=1, active=(0, 1, 3))
    a.persist(prefix + ".0.json")
    b = _dir(eps={4: "h:9"}, epoch=2, active=(0, 1, 3, 4),
             owner={0: 0, 1: 1, 2: 4, 3: 3})
    b.persist(prefix + ".1.json")
    assert not os.path.exists(prefix + ".0.json.tmp")  # atomic replace
    merged = gang.GangDirectory.load_any(prefix)
    assert merged.epoch == 2            # freshest commit wins
    assert 4 in merged.endpoints        # endpoints union across replicas
    assert merged.rank_owner[2] == 4
    with pytest.raises(FileNotFoundError):
        gang.GangDirectory.load_any(str(tmp_path / "nope"))


def test_directory_load_any_skips_corrupt_replica(tmp_path):
    prefix = str(tmp_path / "gang")
    _dir(epoch=1).persist(prefix + ".0.json")
    with open(prefix + ".1.json", "w") as fh:
        fh.write("{not json")
    merged = gang.GangDirectory.load_any(prefix)
    assert merged.epoch == 1


def test_parse_peers():
    assert gang.parse_peers("h1:10,h2:20") == [("h1", 10), ("h2", 20)]
    with pytest.raises(ValueError):
        gang.parse_peers("nocolon")
    with pytest.raises(ValueError):
        gang.parse_peers("")


# ---------------------------------------------------------------------------
# Placement-aware admission
# ---------------------------------------------------------------------------

def test_choose_admission_ranks_without_model_is_lowest_ids():
    assert gang.choose_admission_ranks([7, 2, 5], 2) == [2, 5]
    assert gang.choose_admission_ranks([3], 5) == [3]


def test_choose_admission_ranks_prices_through_placement_model():
    """With a live interconnect model, the vacant seat CLOSEST to the
    active ranks' devices wins — not the lowest id."""
    from bluefog_tpu.ops import placement
    model = placement.synthetic_torus((4, 4))  # 16 devices, 4x4 torus
    placement.set_active(model, None)
    try:
        # Active rank on device 0; vacant seats 1 (adjacent) and 10
        # (diagonally across the torus).
        d1 = model.distance(1, 0)
        d10 = model.distance(10, 0)
        assert d1 < d10  # the oracle the choice must follow
        picked = gang.choose_admission_ranks([1, 10], 1,
                                             active_ranks=[0])
        assert picked == [1]
        # Equal prices break ties by rank id (deterministic across
        # processes): devices 1 and 4 are both one hop from 0.
        assert model.distance(4, 0) == model.distance(1, 0)
        assert gang.choose_admission_ranks([4, 1], 1,
                                           active_ranks=[0]) == [1]
    finally:
        placement.set_active(None, None)


# ---------------------------------------------------------------------------
# Wire handling / registry
# ---------------------------------------------------------------------------

def test_handle_wire_drops_garbage_and_without_service():
    gang.handle_wire(b"not json")       # no service: dropped, no crash
    gang.handle_wire(b"\xff\xfe junk")  # undecodable: logged, dropped
    gang.handle_wire(json.dumps({"k": "dir", "dir": {"n_ranks": 4}})
                     .encode())


def test_handle_wire_resolves_join_waiter_without_service():
    """A joining process has no installed service when its grant lands —
    the nonce waiter alone must resolve it."""
    import threading
    ev = threading.Event()
    gang._join_waiters["abc"] = [ev, None]
    try:
        gang.handle_wire(json.dumps(
            {"k": "grant", "nonce": "abc", "proc": 4, "ranks": [2],
             "n_ranks": 4}).encode())
        assert ev.is_set()
        assert gang._join_waiters["abc"][1]["proc"] == 4
    finally:
        gang._join_waiters.pop("abc", None)


def test_grant_decode_round_trip():
    rows = np.arange(6, dtype=np.float32).reshape(2, 3)
    import base64
    msg = {
        "k": "grant", "proc": 5, "ranks": [2], "epoch": 3,
        "active": [0, 1, 3], "n_ranks": 4,
        "rank_owner": {"0": 0, "1": 1, "2": 2, "3": 3},
        "endpoints": {"0": "h:1", "1": "h:2", "3": "h:4"},
        "windows": {"w": {"shape": [2, 3], "dtype": "float32",
                          "rows": {"2": base64.b64encode(
                              rows.tobytes()).decode()}}},
    }
    g = gang._decode_grant(msg, "h:9")
    assert g.proc == 5 and g.ranks == (2,) and g.epoch == 3
    assert g.directory.n_ranks == 4
    np.testing.assert_array_equal(g.windows["w"]["rows"][2], rows)


def test_service_summary_and_health(tmp_path):
    svc = gang.GangService(_dir(epoch=2, active=(0, 1, 3)),
                           persist_path=str(tmp_path / "g"))
    gang.install(svc)
    s = gang.health_summary()
    assert s["epoch"] == 2 and s["vacant_ranks"] == [2]
    assert s["active_procs"] == [0, 1, 3]
    # Surfaced on the operator-facing /healthz body and %bfstat.
    hz = telemetry.health()
    assert hz["gang_directory"]["epoch"] == 2
    from bluefog_tpu.run.cluster_repl import bfstat_text  # noqa: F401
    svc.persist()
    snap = telemetry.snapshot()
    assert snap.get("bf_gang_directory_epoch") == 2.0
    # With no distrib the replica lands under the bare prefix.
    assert os.path.exists(str(tmp_path / "g") + ".json")
    gang.install(None)
    assert gang.health_summary() is None


def test_init_elastic_requires_knob_and_env(monkeypatch):
    config.reload()
    with pytest.raises(RuntimeError, match="ELASTIC_JOIN"):
        gang.init_elastic()
    monkeypatch.setenv("BLUEFOG_TPU_ELASTIC_JOIN", "1")
    monkeypatch.delenv("BFTPU_GANG_PEERS", raising=False)
    config.reload()
    with pytest.raises(RuntimeError, match="BFTPU_GANG_PEERS"):
        gang.init_elastic()


def test_join_gang_requires_knob():
    config.reload()
    with pytest.raises(RuntimeError, match="ELASTIC_JOIN"):
        gang.join_gang("h:1")


# ---------------------------------------------------------------------------
# Membership integration: grant bookkeeping
# ---------------------------------------------------------------------------

def test_note_join_validates_rank_claims():
    from bluefog_tpu.ops import membership as M
    ctrl = M.MembershipController(
        4, 0, {r: r for r in range(4)}, send_fn=lambda q, p: None,
        active=(0, 1, 3), epoch=1)
    ctrl.note_join(4, (2,), "h:9")
    assert ctrl.pending_joins[4][0] == (2,)
    assert ctrl.peer_endpoint_hint(4) == ("h", 9)
    # A colliding claim from another proc is ignored.
    ctrl.note_join(5, (2,), "h:10")
    assert 5 not in ctrl.pending_joins
    # Claiming a LIVE rank is ignored too.
    ctrl.note_join(6, (1,), "h:11")
    assert 6 not in ctrl.pending_joins
    # Already-active procs can't "join".
    ctrl.note_join(0, (2,), "h:12")


def test_pending_join_expires_when_joiner_dies():
    from bluefog_tpu.ops import membership as M
    clock = [0.0]
    ctrl = M.MembershipController(
        4, 0, {r: r for r in range(4)}, send_fn=lambda q, p: None,
        probe_fn=lambda q: True, now_fn=lambda: clock[0],
        suspect_sec=1.0, active=(0, 1, 3), epoch=1)
    ctrl.note_join(4, (2,), "h:9")
    clock[0] = 0.5
    ctrl.tick()
    assert 4 in ctrl.pending_joins
    clock[0] = 2.0  # the joiner never heartbeat: its claim ages out
    ctrl.tick()
    assert 4 not in ctrl.pending_joins
    assert ctrl.epoch == 1  # and no grow epoch ever committed


# ---------------------------------------------------------------------------
# Launcher: --elastic / --join / --grow + gang growth in _wait_gang
# ---------------------------------------------------------------------------

def test_bfrun_parser_accepts_elastic_flags():
    from bluefog_tpu.run.run import build_parser
    a = build_parser().parse_args(
        ["-np", "4", "--elastic", "--grow", "5", "--gang-dir", "/tmp/g",
         "python", "x.py"])
    assert a.elastic and a.grow == 5.0 and a.gang_dir == "/tmp/g"
    a = build_parser().parse_args(
        ["-np", "1", "--join", "@/tmp/g", "python", "x.py"])
    assert a.join == "@/tmp/g"


def test_bfrun_rejects_bad_elastic_combos(capsys):
    from bluefog_tpu.run import run as R
    assert R.main(["-np", "4", "--join", "h:1", "python", "x.py"]) == 2
    assert "-np 1" in capsys.readouterr().err
    assert R.main(["-np", "4", "--grow", "5", "python", "x.py"]) == 2
    assert "--elastic" in capsys.readouterr().err


def test_child_env_elastic_exports(tmp_path):
    from bluefog_tpu.run import run as R
    args = R.build_parser().parse_args(
        ["-np", "2", "--devices-per-proc", "1", "--elastic",
         "python", "x.py"])
    env = R._child_env(args, "h:1", 1, gang_peers="h:10,h:11",
                       gang_dir=str(tmp_path / "g"))
    assert env["BFTPU_GANG_PEERS"] == "h:10,h:11"
    assert env["BLUEFOG_TPU_ELASTIC_JOIN"] == "1"
    assert env["BLUEFOG_TPU_CHURN"] == "1"
    assert env["BLUEFOG_TPU_GANG_DIR_PATH"] == str(tmp_path / "g")
    # Every elastic member forges the WHOLE world: 2 procs x 1 device.
    assert env["BFTPU_LOCAL_DEVICES"] == "2"
    # A --join child names the world size directly...
    jargs = R.build_parser().parse_args(
        ["-np", "1", "--devices-per-proc", "4", "--join", "@/t/g",
         "python", "x.py"])
    jenv = R._child_env(jargs, "h:1", 0, join_target="@/t/g")
    assert jenv["BFTPU_GANG_JOIN"] == "@/t/g"
    assert jenv["BFTPU_LOCAL_DEVICES"] == "4"
    # ...while a --grow joiner inherits the gang's world via join_world.
    genv = R._child_env(args, "h:1", 2, join_target="@/t/g", join_world=2)
    assert genv["BFTPU_LOCAL_DEVICES"] == "2"


class _FakeProc:
    def __init__(self, rc=None):
        self.rc = rc

    def poll(self):
        return self.rc

    def wait(self, timeout=None):
        return self.rc

    def terminate(self):
        pass

    def kill(self):
        pass


def test_wait_gang_supervises_grown_member():
    """Satellite: _wait_gang tolerates gang GROWTH — a joined process is
    spawned mid-wait, supervised, and its exit reason reported."""
    import time as _time

    from bluefog_tpu.run import run as R
    founder = _FakeProc(None)  # still running when the grow fires
    entries = [(founder, "127.0.0.1", False)]
    joined = _FakeProc(None)

    def spawn():
        entries.append((joined, "127.0.0.1", False))
        joined.rc = 0   # the joiner finishes clean shortly after
        founder.rc = 0  # ...and so does the founding rank

    rc = R._wait_gang(entries, ["ssh"], "tag",
                      grow=[(_time.monotonic() + 0.05, spawn)])
    assert rc == 0
    assert len(entries) == 2  # the joined member was supervised


def test_wait_gang_skips_grow_after_clean_finish(capsys):
    """A gang that finished before the scheduled grow has nothing to
    grow into: the spawn is skipped and the run stays successful."""
    import time as _time

    from bluefog_tpu.run import run as R
    entries = [(_FakeProc(0), "127.0.0.1", False)]
    fired = []
    rc = R._wait_gang(entries, ["ssh"], "tag",
                      grow=[(_time.monotonic() + 60.0,
                             lambda: fired.append(1))])
    assert rc == 0 and not fired
    assert "skipping" in capsys.readouterr().err


def test_wait_gang_grown_member_failure_kills_gang(capsys):
    import time as _time

    from bluefog_tpu.run import run as R
    survivor = _FakeProc(None)
    entries = [(survivor, "127.0.0.1", False)]

    def spawn():
        entries.append((_FakeProc(3), "127.0.0.1", False))
        survivor.rc = 0

    rc = R._wait_gang(entries, ["ssh"], "tag",
                      grow=[(_time.monotonic(), spawn)])
    assert rc == 3  # the grown rank's failure is NOT silently ignored
    assert "rank 1: exit 3" in capsys.readouterr().err


def test_wait_gang_failed_grow_spawn_is_fatal(capsys):
    import time as _time

    from bluefog_tpu.run import run as R
    entries = [(_FakeProc(None), "127.0.0.1", False)]

    def spawn():
        raise OSError("no joiner for you")

    rc = R._wait_gang(entries, ["ssh"], "tag",
                      grow=[(_time.monotonic(), spawn)])
    assert rc == 1
    assert "failed to grow" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# Satellite: stale owned_ranks.json invalidation for GROWN gangs
# ---------------------------------------------------------------------------

def _write_map(base, idx, ranks, nproc=None):
    from bluefog_tpu.utils import elastic
    d = os.path.join(base, f"proc{idx}")
    os.makedirs(d, exist_ok=True)
    body = ranks if nproc is None else {"ranks": ranks, "nproc": nproc}
    with open(os.path.join(d, elastic._OWNED_FILE), "w") as fh:
        json.dump(body, fh)
    return os.path.join(d, elastic._OWNED_FILE)


def test_owned_map_parses_both_formats():
    from bluefog_tpu.utils import elastic
    assert elastic._parse_owned_map([0, 1]) == ([0, 1], None)
    assert elastic._parse_owned_map({"ranks": [2], "nproc": 4}) == ([2], 4)
    assert elastic._parse_owned_map({"ranks": [2]}) == ([2], None)


def test_invalidate_owned_ranks_on_growth(tmp_path, caplog):
    """A resume after a JOIN (3 -> 4 processes) must not resurrect the
    pre-join ownership maps: surviving dirs stamped nproc=3 are
    invalidated (renamed .stale + warned), not silently reused."""
    import logging

    from bluefog_tpu.utils import elastic
    from bluefog_tpu.utils.logging import get_logger
    base = str(tmp_path)
    for i, ranks in enumerate([[0, 1], [2], [3]]):
        _write_map(base, i, ranks, nproc=3)
    log = get_logger()
    log.addHandler(caplog.handler)
    try:
        with caplog.at_level(logging.WARNING, logger="bluefog_tpu"):
            elastic._invalidate_stale_owned_ranks(base, 4)
    finally:
        log.removeHandler(caplog.handler)
    for i in range(3):
        f = os.path.join(base, f"proc{i}", elastic._OWNED_FILE)
        assert not os.path.exists(f)
        assert os.path.exists(f + ".stale")
    assert any("must not resurrect" in r.message for r in caplog.records)


def test_invalidate_owned_ranks_keeps_current_geometry(tmp_path):
    from bluefog_tpu.utils import elastic
    base = str(tmp_path)
    paths = [_write_map(base, i, [i], nproc=2) for i in range(2)]
    elastic._invalidate_stale_owned_ranks(base, 2)
    for p in paths:
        assert os.path.exists(p)  # matching stamp: untouched


def test_invalidate_owned_ranks_legacy_files_untouched_below_nproc(
        tmp_path):
    """Pre-stamp (bare list) files carry no geometry: within the live
    process range they are kept (the historical behavior), while dirs
    beyond the new count are still retired."""
    from bluefog_tpu.utils import elastic
    base = str(tmp_path)
    keep = _write_map(base, 0, [0, 1])           # legacy, idx < nproc
    drop = _write_map(base, 3, [3], nproc=4)     # beyond the new count
    elastic._invalidate_stale_owned_ranks(base, 2)
    assert os.path.exists(keep)
    assert not os.path.exists(drop)
    assert os.path.exists(drop + ".stale")


def test_owned_rows_of_reads_stamped_maps(tmp_path):
    from bluefog_tpu.utils import elastic
    base = str(tmp_path)
    _write_map(base, 0, [0, 2], nproc=2)
    _write_map(base, 1, [1, 3], nproc=2)
    dirs = [os.path.join(base, f"proc{i}") for i in range(2)]
    assert elastic._owned_rows_of(dirs, 4) == [[0, 2], [1, 3]]


# ---------------------------------------------------------------------------
# Config knobs
# ---------------------------------------------------------------------------

def test_gang_config_defaults(monkeypatch):
    cfg = config.reload()
    assert cfg.elastic_join is False
    assert cfg.gang_dir_path is None
    assert cfg.join_timeout_ms == 30000.0
    monkeypatch.setenv("BLUEFOG_TPU_ELASTIC_JOIN", "1")
    monkeypatch.setenv("BLUEFOG_TPU_GANG_DIR_PATH", "/tmp/gg")
    monkeypatch.setenv("BLUEFOG_TPU_JOIN_TIMEOUT_MS", "5000")
    cfg = config.reload()
    assert cfg.elastic_join and cfg.gang_dir_path == "/tmp/gg"
    assert cfg.join_timeout_ms == 5000.0


def test_bf_gang_info_export():
    import bluefog_tpu as bf
    assert bf.gang_info() is None
    svc = gang.GangService(_dir(), persist_path=None)
    gang.install(svc)
    assert bf.gang_info()["epoch"] == 0


# ---------------------------------------------------------------------------
# Full gang (slow tier; `make chaos-smoke` runs the same harness in CI)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_join_smoke_end_to_end():
    import subprocess
    import sys
    r = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.tools", "chaos",
         "--join-smoke"],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "chaos join OK" in r.stdout


@pytest.mark.slow
def test_kill0_smoke_end_to_end():
    import subprocess
    import sys
    r = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.tools", "chaos",
         "--kill0-smoke"],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "chaos kill-rank-0 OK" in r.stdout
