"""Test harness: run the whole suite on a virtual 8-device CPU mesh.

The reference framework can only test multi-process behavior under
``mpirun -np N`` (BlueFog ``Makefile:28-51``); here XLA's host-platform device
multiplexing gives a real fake-cluster on one process, so every topology /
collective / optimizer test runs against 8 "ranks" with zero launchers.
"""

import os

# Must be set before jax initializes its backends.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

# Site hooks may have pinned another platform via jax.config; the config
# knob wins over the env var, so set it too.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs[:8]


@pytest.fixture(autouse=True)
def _reset_bluefog_state():
    """Each test gets a pristine module-level bluefog context, including the
    window store (a failing test must not leak windows into the next one)."""
    yield
    try:
        from bluefog_tpu import basics
        from bluefog_tpu.ops import window
        window._free_all_windows()
        basics._reset_for_tests()
    except (ImportError, AttributeError):
        pass


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running multi-process integration test")


_TPU_PROBE: dict = {}


def tpu_subprocess_env():
    """Env for a clean real-backend subprocess (the in-process suite pins
    CPU), with a session-cached reachability probe.

    Outcomes: skip when no TPU is attached; skip when the accelerator
    tunnel hangs backend init (infra outage, not a code regression); FAIL
    when the probe subprocess errors — a crashing plugin or broken install
    must not masquerade as a skip and silently stop the only tests that
    run the real Mosaic kernels."""
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "BFTPU_LOCAL_DEVICES")}
    # PREPEND to PYTHONPATH: TPU plugins can ride site hooks living there.
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    if not _TPU_PROBE:
        try:
            ping = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print('BACKEND', jax.default_backend())"],
                env=env, capture_output=True, text=True, timeout=120)
            if ping.returncode != 0:
                _TPU_PROBE.update(status="error",
                                  detail=ping.stderr[-2000:])
            elif "BACKEND tpu" in ping.stdout:
                _TPU_PROBE.update(status="tpu", detail="")
            else:
                _TPU_PROBE.update(status="other", detail=ping.stdout)
        except subprocess.TimeoutExpired:
            _TPU_PROBE.update(status="hang", detail="")
    status = _TPU_PROBE["status"]
    if status == "hang":
        pytest.skip("accelerator backend unreachable (init hang)")
    if status == "error":
        raise AssertionError(
            "backend probe subprocess failed (broken install/plugin?):\n"
            + _TPU_PROBE["detail"])
    if status != "tpu":
        pytest.skip("no TPU attached")
    return env
