"""Test harness: run the whole suite on a virtual 8-device CPU mesh.

The reference framework can only test multi-process behavior under
``mpirun -np N`` (BlueFog ``Makefile:28-51``); here XLA's host-platform device
multiplexing gives a real fake-cluster on one process, so every topology /
collective / optimizer test runs against 8 "ranks" with zero launchers.
"""

import os

# Must be set before jax initializes its backends.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

# Site hooks may have pinned another platform via jax.config; the config
# knob wins over the env var, so set it too.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs[:8]


@pytest.fixture(autouse=True)
def _reset_bluefog_state():
    """Each test gets a pristine module-level bluefog context, including the
    window store (a failing test must not leak windows into the next one)."""
    yield
    try:
        from bluefog_tpu import basics
        from bluefog_tpu.ops import window
        window._free_all_windows()
        basics._reset_for_tests()
    except (ImportError, AttributeError):
        pass


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running multi-process integration test")
