"""Sharded-aware gossip (``ops/sharded.py`` + ``BLUEFOG_TPU_SHARDED_GOSSIP``).

Planner unit tests (partition-spec -> gossip mask, per-group schedule
compilation, slice row extract/scatter, induced window weights), the
eager collective and window paths against dense / per-group oracles,
the bit-identity hatches (knob off, fully replicated tree), the
per-shard telemetry split, and the fused-step composition (put-plan
skip + fused-vs-eager oracle).  The slow bfrun leg drives a simulated
MoE tree across real processes and asserts replicated consensus with
experts mixing inside their replica group only.
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import bluefog_tpu as bf
from bluefog_tpu import topology as topo
from bluefog_tpu.ops import schedule as S
from bluefog_tpu.ops import sharded as SH
from bluefog_tpu.utils import config

N = 8

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tree(n=N, seed=0):
    rng = np.random.RandomState(seed)
    return {"a": jnp.asarray(rng.randn(n, 5), jnp.float32),
            "b": jnp.asarray(rng.randn(n, 4, 8), jnp.float32)}


SPECS = {"a": P(), "b": P(None, "tp")}


# ---------------------------------------------------------------------------
# Planner
# ---------------------------------------------------------------------------

def test_build_plan_mask_dims_fraction():
    tree = _tree()
    plan = SH.build_plan(tree, SPECS, n=N, n_shards=2)
    # tree-flatten order is alphabetical: a then b.
    assert plan.mask == (False, True)
    assert plan.dims == (None, 1)
    assert plan.any_sharded
    assert plan.n_shards == 2
    assert plan.groups == ((0, 1, 2, 3), (4, 5, 6, 7))
    # bytes: a = 5 f32, b = 32 f32 per rank row.
    assert plan.rep_bytes == N * 5 * 4
    assert plan.sh_bytes == N * 32 * 4
    assert abs(plan.replicated_fraction - 5 / 37) < 1e-12
    assert plan.decisions == ("replicated", "sharded(dim=1)")


def test_build_plan_signature_keys_cache():
    tree = _tree()
    p1 = SH.build_plan(tree, SPECS, n=N, n_shards=2)
    p2 = SH.build_plan(tree, SPECS, n=N, n_shards=2)
    assert p1.signature == p2.signature
    assert hash(p1.signature) == hash(p2.signature)
    p3 = SH.build_plan(tree, {"a": P(), "b": P()}, n=N, n_shards=2)
    assert p3.signature != p1.signature


def test_build_plan_indivisible_falls_back_to_replicated():
    tree = {"w": jnp.zeros((N, 7, 3), jnp.float32)}
    plan = SH.build_plan(tree, {"w": P("ep", None)}, n=N, n_shards=2)
    assert plan.mask == (False,)
    assert not plan.any_sharded
    assert "indivisible" in plan.decisions[0]
    assert plan.replicated_fraction == 1.0


def test_build_plan_requires_grouping_when_sharded():
    tree = _tree()
    with pytest.raises(ValueError, match="n_shards"):
        SH.build_plan(tree, SPECS, n=N)


def test_build_plan_keeps_groups_for_all_replicated_tree():
    """An all-replicated plan under explicit groups still classifies
    edges by those groups — the smoke's DCN ratio baseline."""
    tree = {"a": jnp.zeros((N, 3), jnp.float32)}
    plan = SH.build_plan(tree, {"a": P()}, n=N, n_shards=2)
    assert not plan.any_sharded
    assert plan.groups == ((0, 1, 2, 3), (4, 5, 6, 7))


def test_default_groups_and_validation():
    assert SH.default_groups(8, 4) == ((0, 1), (2, 3), (4, 5), (6, 7))
    with pytest.raises(ValueError):
        SH.default_groups(8, 3)
    with pytest.raises(ValueError):  # not a partition of range(n)
        SH.build_plan(_tree(), SPECS, n=N, groups=((0, 1), (1, 2)))


def test_group_schedules_never_cross_groups():
    groups = SH.default_groups(N, 2)
    merged, per_group = SH.compile_group_schedules(N, groups)
    coords = tuple(0 if r < 4 else 1 for r in range(N))
    gsets = [set(g) for g in groups]
    for rnd in merged.rounds:
        for (s, d) in rnd.pairs:
            assert any(s in g and d in g for g in gsets), (s, d)
    assert len(per_group) == 2
    assert per_group[0][0] == (0, 1, 2, 3)
    # merged rounds = max over groups (round r of every group merges).
    assert len(merged.rounds) == max(
        len(sub.rounds) for _g, sub in per_group)
    ici, dcn = SH.edge_level_counts(coords, merged)
    assert dcn == 0.0 and ici > 0


def test_edge_level_counts_exp2_8():
    coords = tuple(0 if r < 4 else 1 for r in range(N))
    sched = S.compile_static(topo.ExponentialTwoGraph(N))
    ici, dcn = SH.edge_level_counts(coords, sched)
    assert (ici, dcn) == (10.0, 14.0)


def test_own_shard_rows_roundtrip():
    rng = np.random.RandomState(3)
    leaf = rng.randn(N, 4, 8).astype(np.float32)
    coords = tuple(0 if r < 4 else 1 for r in range(N))
    rows = SH.own_shard_rows(leaf, 1, coords, 2)
    assert rows.shape == (N, 4 * 4)
    for r in range(N):
        c = coords[r]
        np.testing.assert_array_equal(
            rows[r], leaf[r, :, c * 4:(c + 1) * 4].ravel())
    back = SH.scatter_shard_rows(leaf, rows, 1, coords, 2)
    np.testing.assert_array_equal(back, leaf)


def test_induced_window_weights_in_group_only():
    plan = SH.build_plan(_tree(), SPECS, n=N, n_shards=2)
    put_edges, self_w, nbr_w = SH.induced_window_weights(
        plan, topo.ExponentialTwoGraph(N))
    gsets = [set(g) for g in plan.groups]
    for (s, d) in put_edges:
        assert any(s in g and d in g for g in gsets), (s, d)
    indeg = np.zeros(N)
    for (d, _s) in nbr_w:
        indeg[d] += 1
    np.testing.assert_allclose(self_w, 1.0 / (indeg + 1))
    for (d, s), w in nbr_w.items():
        assert w == self_w[d]


# ---------------------------------------------------------------------------
# Eager collective path
# ---------------------------------------------------------------------------

def test_collective_dense_oracle_and_ghost_isolation():
    bf.init(lambda: topo.ExponentialTwoGraph(N))
    params = _tree()
    grads = jax.tree.map(jnp.zeros_like, params)
    opt = bf.optim.DistributedNeighborAllreduceOptimizer(
        optax.sgd(0.0), shard_specs=SPECS, num_shards=2)
    out, _ = opt.step(params, grads, opt.init(params))

    W = topo.weight_matrix(bf.load_topology())
    exp_a = W.T @ np.asarray(params["a"])
    assert np.abs(np.asarray(out["a"]) - exp_a).max() < 1e-6

    plan = opt._shard_plan(params)
    _m, per = SH.compile_group_schedules(N, plan.groups)
    Wg = np.zeros((N, N))
    for g, _sub in per:
        sw = topo.weight_matrix(topo.ExponentialTwoGraph(len(g)))
        for i, gi in enumerate(g):
            for j, gj in enumerate(g):
                Wg[gi, gj] = sw[i, j]
    b0, b1 = np.asarray(params["b"]), np.asarray(out["b"])
    for r in range(N):
        c = plan.coords[r]
        own = b0[:, :, c * 4:(c + 1) * 4]
        exp = np.einsum("s,s...->...", Wg[:, r], own)
        assert np.abs(b1[r, :, c * 4:(c + 1) * 4] - exp).max() < 1e-6, r
        # Ghost region (the other coordinate's chunk) is bit-untouched.
        o = 1 - c
        np.testing.assert_array_equal(
            b1[r, :, o * 4:(o + 1) * 4], b0[r, :, o * 4:(o + 1) * 4])


def test_collective_fully_replicated_bitwise_knob_both_ways(monkeypatch):
    bf.init(lambda: topo.ExponentialTwoGraph(N))
    params = _tree()
    grads = jax.tree.map(jnp.zeros_like, params)

    def drive(specs=None, num_shards=None):
        opt = bf.optim.DistributedNeighborAllreduceOptimizer(
            optax.sgd(0.0), shard_specs=specs, num_shards=num_shards)
        out, _ = opt.step(params, grads, opt.init(params))
        return out

    base = drive()
    allrep = drive({"a": P(), "b": P()}, 2)
    monkeypatch.setenv("BLUEFOG_TPU_SHARDED_GOSSIP", "0")
    config.reload()
    try:
        off = drive(SPECS, 2)
    finally:
        monkeypatch.delenv("BLUEFOG_TPU_SHARDED_GOSSIP")
        config.reload()
    for k in base:
        np.testing.assert_array_equal(np.asarray(allrep[k]),
                                      np.asarray(base[k]), err_msg=k)
        np.testing.assert_array_equal(np.asarray(off[k]),
                                      np.asarray(base[k]), err_msg=k)


def test_gradient_allreduce_rejects_shard_specs():
    bf.init(lambda: topo.ExponentialTwoGraph(N))
    with pytest.raises(ValueError, match="shard"):
        bf.optim.DistributedGradientAllreduceOptimizer(
            optax.sgd(0.1), shard_specs=SPECS, num_shards=2)


def test_shard_telemetry_labels(monkeypatch):
    from bluefog_tpu.utils import telemetry
    monkeypatch.setenv("BLUEFOG_TPU_TELEMETRY", "1")
    config.reload()
    try:
        bf.init(lambda: topo.ExponentialTwoGraph(N))
        telemetry.reset()
        params = _tree()
        grads = jax.tree.map(jnp.zeros_like, params)
        opt = bf.optim.DistributedNeighborAllreduceOptimizer(
            optax.sgd(0.0), shard_specs=SPECS, num_shards=2)
        state = opt.init(params)
        steps = 2
        p = params
        for _ in range(steps):
            p, state = opt.step(p, grads, state)
        snap = telemetry.snapshot()
        rep_row = 5 * 4  # leaf a: 5 f32 per rank row
        sh_row = 32 * 4 / 2  # leaf b: own slice rows
        key = 'bf_comm_level_bytes_total{level="%s",shard="%s"}'
        assert snap[key % ("dcn", "replicated")] == rep_row * 14 * steps
        assert snap[key % ("ici", "replicated")] == rep_row * 10 * steps
        assert snap[key % ("ici", "sharded")] == sh_row * 16 * steps
        # A sharded byte on the DCN is a planner regression.
        assert key % ("dcn", "sharded") not in snap
    finally:
        monkeypatch.delenv("BLUEFOG_TPU_TELEMETRY")
        config.reload()


# ---------------------------------------------------------------------------
# Eager window path
# ---------------------------------------------------------------------------

def test_window_sharded_in_group_oracle():
    bf.init(lambda: topo.ExponentialTwoGraph(N))
    params = _tree(seed=1)
    grads = jax.tree.map(jnp.zeros_like, params)
    opt = bf.optim.DistributedWinPutOptimizer(
        optax.sgd(0.0), shard_specs=SPECS, num_shards=2)
    state = opt.init(params)
    assert opt._names == ["winput.fused", "winput.sharded"]
    out, _ = opt.step(params, grads, state)

    W = topo.weight_matrix(bf.load_topology())
    exp_a = W.T @ np.asarray(params["a"])
    assert np.abs(np.asarray(out["a"]) - exp_a).max() < 1e-5

    plan = opt._shard_plan
    _pe, self_w, nbr_w = SH.induced_window_weights(
        plan, bf.load_topology())
    b0, b1 = np.asarray(params["b"]), np.asarray(out["b"])
    for r in range(N):
        c = plan.coords[r]
        own = b0[:, :, c * 4:(c + 1) * 4]
        exp = self_w[r] * own[r]
        for (d, s), w in nbr_w.items():
            if d == r:
                exp = exp + w * own[s]
        assert np.abs(b1[r, :, c * 4:(c + 1) * 4] - exp).max() < 1e-5, r
        o = 1 - c
        np.testing.assert_array_equal(
            b1[r, :, o * 4:(o + 1) * 4], b0[r, :, o * 4:(o + 1) * 4])
    opt.free()


def test_window_fully_replicated_bitwise():
    bf.init(lambda: topo.ExponentialTwoGraph(N))
    params = _tree(seed=1)
    grads = jax.tree.map(jnp.zeros_like, params)
    o1 = bf.optim.DistributedWinPutOptimizer(
        optax.sgd(0.0), window_prefix="w1",
        shard_specs={"a": P(), "b": P()}, num_shards=2)
    p1, _ = o1.step(params, grads, o1.init(params))
    o1.free()
    o2 = bf.optim.DistributedWinPutOptimizer(
        optax.sgd(0.0), window_prefix="w2")
    p2, _ = o2.step(params, grads, o2.init(params))
    o2.free()
    for k in p1:
        np.testing.assert_array_equal(np.asarray(p1[k]),
                                      np.asarray(p2[k]), err_msg=k)


# ---------------------------------------------------------------------------
# Fused-step composition
# ---------------------------------------------------------------------------

def _drive_fused(monkeypatch, fused, prefix, specs, num_shards, steps=3):
    monkeypatch.setenv("BLUEFOG_TPU_FUSED_STEP", "1" if fused else "0")
    config.reload()
    params = _tree(seed=2)
    grads = _tree(seed=3)
    opt = bf.optim.DistributedWinPutOptimizer(
        optax.sgd(0.0), window_prefix=prefix,
        shard_specs=specs, num_shards=num_shards)
    state = opt.init(params)
    p = params
    for _ in range(steps):
        p, state = opt.step(p, grads, state)
    fi = opt._fused_impl
    stats = (fi.fused_steps, fi.builds) if fi is not None else (0, 0)
    prog = (next(iter(fi._programs.values()))
            if fi is not None and fi._programs else None)
    opt.free()
    return p, stats, prog


def test_fused_step_skips_sharded_put_plans(monkeypatch):
    bf.init(lambda: topo.ExponentialTwoGraph(N))
    try:
        p_f, st, prog = _drive_fused(monkeypatch, True, "wf", SPECS, 2)
        assert st == (3, 1)
        assert prog is not None
        # The program covers the replicated bucket windows only — the
        # put-plan builder skipped the sharded window at compile time.
        assert prog.shard_name == "wf.sharded"
        assert all(not nm.endswith(".sharded") for nm in prog.names)
        assert len(prog.plans) == len(prog.names)
        p_e, st_e, _ = _drive_fused(monkeypatch, False, "we", SPECS, 2)
        assert st_e == (0, 0)
        for k in p_f:
            np.testing.assert_array_equal(
                np.asarray(p_f[k]), np.asarray(p_e[k]),
                err_msg=f"{k}: fused-vs-eager oracle (sharded tree)")
    finally:
        monkeypatch.delenv("BLUEFOG_TPU_FUSED_STEP")
        config.reload()


def test_fused_step_replicated_tree_has_no_shard_window(monkeypatch):
    bf.init(lambda: topo.ExponentialTwoGraph(N))
    try:
        p_r, _st, prog_r = _drive_fused(
            monkeypatch, True, "wr", {"a": P(), "b": P()}, 2)
        p_n, _st2, prog_n = _drive_fused(monkeypatch, True, "wn",
                                         None, None)
        assert prog_r is not None and prog_r.shard_name is None
        assert prog_n is not None and prog_n.shard_name is None
        for k in p_r:
            np.testing.assert_array_equal(
                np.asarray(p_r[k]), np.asarray(p_n[k]), err_msg=k)
    finally:
        monkeypatch.delenv("BLUEFOG_TPU_FUSED_STEP")
        config.reload()


def test_fused_key_carries_plan_signature(monkeypatch):
    """Same tree with and without specs must compile DIFFERENT programs."""
    bf.init(lambda: topo.ExponentialTwoGraph(N))
    try:
        _p, _st, prog_a = _drive_fused(monkeypatch, True, "ka", SPECS, 2)
        _p2, _st2, prog_b = _drive_fused(monkeypatch, True, "ka",
                                         None, None)
        assert prog_a.key != prog_b.key
    finally:
        monkeypatch.delenv("BLUEFOG_TPU_FUSED_STEP")
        config.reload()


# ---------------------------------------------------------------------------
# Multi-process MoE convergence (slow)
# ---------------------------------------------------------------------------

_MOE_SCRIPT = r"""
import sys
sys.path.insert(0, "@REPO@")
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import optax
import bluefog_tpu as bf
from jax.sharding import PartitionSpec as P

bf.init_distributed()
n = bf.size()
assert n == 8, n
rng = np.random.RandomState(11)
# Simulated MoE transformer block: replicated attention + router,
# 2-way expert-sharded FFN. Groups: ranks 0-3 hold expert slice 0,
# ranks 4-7 hold slice 1 — each group starts from its own expert
# values, and only in-group gossip may mix them.
params = {"attn": jnp.asarray(rng.randn(n, 16), jnp.float32),
          "experts": jnp.asarray(rng.randn(n, 4, 8), jnp.float32)}
grads = jax.tree.map(jnp.zeros_like, params)
opt = bf.optim.DistributedNeighborAllreduceOptimizer(
    optax.sgd(0.0), shard_specs={"attn": P(), "experts": P(None, "ep")},
    num_shards=2)
state = opt.init(params)
p = params
for _ in range(24):
    p, state = opt.step(p, grads, state)

attn = bf.to_numpy(p["attn"]) if hasattr(bf, "to_numpy") else np.asarray(p["attn"])
experts = bf.to_numpy(p["experts"]) if hasattr(bf, "to_numpy") else np.asarray(p["experts"])
a0 = np.asarray(params["attn"])
e0 = np.asarray(params["experts"])

# Replicated consensus: every rank converges to the global mean.
target = a0.mean(axis=0)
spread = np.abs(attn - target).max()
assert spread < 1e-3, f"replicated leaf did not reach consensus: {spread}"

# Sharded consensus is PER GROUP and per slice: each rank's own slice
# converges to its group's mean of that slice; the ghost slice is
# bit-untouched (still the initial values).
groups = [list(range(0, 4)), list(range(4, 8))]
for gi, g in enumerate(groups):
    for c, sl in ((gi, slice(gi * 4, gi * 4 + 4)),):
        tgt = e0[g][:, :, sl].mean(axis=0)
        for r in g:
            d = np.abs(experts[r, :, sl] - tgt).max()
            assert d < 1e-3, f"rank {r} slice {c}: {d}"
            other = slice((1 - gi) * 4, (1 - gi) * 4 + 4)
            np.testing.assert_array_equal(experts[r, :, other],
                                          e0[r, :, other])

# Cross-group isolation: the two groups' slice means stay DIFFERENT
# (nothing leaked across the expert boundary).
m0 = e0[0:4][:, :, 0:4].mean(axis=0)
m1 = e0[4:8][:, :, 4:8].mean(axis=0)
assert np.abs(m0 - m1).max() > 1e-3
print("MOE_SHARDED_OK")
"""


@pytest.mark.slow
def test_multiprocess_moe_sharded_convergence(tmp_path):
    script = tmp_path / "prog.py"
    script.write_text(_MOE_SCRIPT.replace("@REPO@", REPO))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.run", "-np", "2",
         "--devices-per-proc", "4", sys.executable, str(script)],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env)
    assert out.returncode == 0, \
        f"stdout={out.stdout}\nstderr={out.stderr[-4000:]}"
    assert "MOE_SHARDED_OK" in out.stdout
