"""Coalesced DCN window transport (PR 4): OP_BATCH wire framing, per-peer
sender workers, ordering under coalescing, the vectorized batched apply,
and the transient-send retry.

Since the native hot path (BLUEFOG_TPU_WIN_NATIVE, winsvc.cc bf_wintx_* +
bf_winsvc_drain) moved batching/encode/decode/fold into C++, this file is
also the cross-path ORACLE: the loopback tests run under whichever path
the environment selects, and the dedicated tests at the bottom assert
that native-encoded frames decode bit-identically through the Python
decoder (and vice versa) and that the folded native drain produces
bit-identical window state to the Python batched apply."""

import os
import threading

import numpy as np
import pytest

import bluefog_tpu as bf
from bluefog_tpu import native
from bluefog_tpu import topology as topo
from bluefog_tpu.ops import transport as T
from bluefog_tpu.ops import window as W
from bluefog_tpu.utils import config, telemetry

needs_native = pytest.mark.skipif(not native.available(),
                                  reason="native core not built")

_ALL_OPS = (T.OP_PUT, T.OP_ACCUMULATE, T.OP_GET_REQ, T.OP_GET_REPLY,
            T.OP_FENCE_REQ, T.OP_FENCE_ACK, T.OP_MUTEX_ACQ,
            T.OP_MUTEX_GRANT, T.OP_MUTEX_REL)


@pytest.fixture
def coalesce_env(monkeypatch):
    """Set coalescing knobs for a test and restore the config cache after."""
    def set_env(**kv):
        for k, v in kv.items():
            monkeypatch.setenv(k, str(v))
        config.reload()
    yield set_env
    config.reload()


# ---------------------------------------------------------------------------
# Wire framing
# ---------------------------------------------------------------------------

def test_batch_roundtrip_property():
    """Random batches of mixed ops — bf16-flagged payloads, zero-length
    fence/mutex messages, awkward names — encode -> decode bit-identically
    (pure Python: the framing has no native dependency)."""
    rng = np.random.RandomState(0)
    names = ["w", "", "very.long/param:name", "π-window", "x" * 127]
    for _ in range(50):
        count = int(rng.randint(1, 40))
        msgs = []
        for _ in range(count):
            op = int(rng.choice(_ALL_OPS))
            if op in (T.OP_PUT, T.OP_ACCUMULATE) and rng.rand() < 0.3:
                op |= T.OP_BF16_FLAG
            payload = rng.bytes(int(rng.choice([0, 1, 7, 64, 4096])))
            msgs.append((op, str(rng.choice(names)), int(rng.randint(-1, 64)),
                         int(rng.randint(-1, 64)), float(rng.randn()),
                         float(rng.randn()), payload))
        blob = T._encode_batch(msgs)
        out = T._decode_batch(memoryview(blob))
        assert len(out) == len(msgs)
        for a, b in zip(msgs, out):
            assert a[:6] == b[:6]
            assert a[6] == bytes(b[6])  # payload bit-identical


def test_batch_decode_rejects_bad_version_and_trailing_bytes():
    msgs = [(T.OP_PUT, "w", 0, 1, 1.0, 0.0, b"\x01\x02")]
    blob = bytearray(T._encode_batch(msgs))
    blob[0] = T.BATCH_VERSION + 1
    with pytest.raises(ValueError, match="version"):
        T._decode_batch(bytes(blob))
    with pytest.raises(ValueError, match="trailing"):
        T._decode_batch(T._encode_batch(msgs) + b"\x00")


# ---------------------------------------------------------------------------
# Loopback: ordering, fence-after-puts, env hatch
# ---------------------------------------------------------------------------

class _Recorder:
    def __init__(self):
        self.msgs = []
        self.batches = 0
        self.cv = threading.Condition()

    def apply(self, op, name, src, dst, weight, p_weight, payload):
        with self.cv:
            # payload is a zero-copy view into the recv buffer — snapshot.
            self.msgs.append((op, name, src, dst, weight, p_weight,
                              bytes(payload)))
            self.cv.notify_all()

    def apply_batch(self, msgs):
        self.batches += 1
        for m in msgs:
            self.apply(*m)

    def wait_for(self, n, timeout=20):
        with self.cv:
            ok = self.cv.wait_for(lambda: len(self.msgs) >= n,
                                  timeout=timeout)
        assert ok, f"only {len(self.msgs)}/{n} messages arrived"


@needs_native
def test_loopback_coalesced_preserves_fifo_and_fence_ordering(coalesce_env):
    """With coalescing ON (the default), a burst of puts followed by a
    FENCE_REQ arrives in exact send order — the fence trails every put on
    the same stream, which is the property win_fence's ack certification
    rests on — and the puts actually travel batched."""
    coalesce_env(BLUEFOG_TPU_WIN_COALESCE=1,
                 BLUEFOG_TPU_WIN_COALESCE_LINGER_MS=5)
    rec = _Recorder()
    server = T.WindowTransport(rec.apply, apply_batch=rec.apply_batch)
    client = T.WindowTransport(lambda *a: None)
    try:
        n_puts = 64
        for i in range(n_puts):
            client.send("127.0.0.1", server.port, T.OP_PUT, "w", i, 0,
                        float(i), np.full(8, i, np.float32), p_weight=0.5)
        client.send("127.0.0.1", server.port, T.OP_FENCE_REQ, "", 0, -1,
                    0.0, np.zeros(0, np.float32))
        client.flush()
        rec.wait_for(n_puts + 1)
        ops = [m[0] for m in rec.msgs]
        assert ops[-1] == T.OP_FENCE_REQ  # fence NEVER overtakes a put
        assert ops[:-1] == [T.OP_PUT] * n_puts
        assert [m[2] for m in rec.msgs[:-1]] == list(range(n_puts))  # FIFO
        for i, m in enumerate(rec.msgs[:-1]):  # payloads land intact
            np.testing.assert_array_equal(
                np.frombuffer(m[6], np.float32), np.full(8, i, np.float32))
        assert rec.batches >= 1, "coalescing on but nothing batched"
    finally:
        client.stop()
        server.stop()


@needs_native
def test_coalesce_env_hatch_restores_per_message_path(coalesce_env):
    """BLUEFOG_TPU_WIN_COALESCE=0: every message is its own native frame
    (no batch frames at the receiver), same delivery, same order."""
    coalesce_env(BLUEFOG_TPU_WIN_COALESCE=0)
    rec = _Recorder()
    server = T.WindowTransport(rec.apply, apply_batch=rec.apply_batch)
    client = T.WindowTransport(lambda *a: None)
    try:
        assert not client.coalesce
        for i in range(8):
            client.send("127.0.0.1", server.port, T.OP_ACCUMULATE, "w",
                        i, 0, 1.0, np.full(4, i, np.float32))
        client.flush()  # no-op on the legacy path (no queues exist)
        rec.wait_for(8)
        assert rec.batches == 0
        assert [m[2] for m in rec.msgs] == list(range(8))
    finally:
        client.stop()
        server.stop()


@needs_native
def test_send_retry_counts_telemetry_and_raises(coalesce_env):
    """A dead endpoint: the native send is retried once with backoff
    (bf_win_tx_retries_total counts it) and then raises ConnectionError —
    synchronously on the legacy path, at flush() on the coalesced path."""
    import socket
    dead = socket.socket()
    dead.bind(("127.0.0.1", 0))  # bound, never listening: connect refused
    port = dead.getsockname()[1]
    telemetry.reset()
    try:
        coalesce_env(BLUEFOG_TPU_WIN_COALESCE=0)
        direct = T.WindowTransport(lambda *a: None)
        try:
            with pytest.raises(ConnectionError):
                direct.send("127.0.0.1", port, T.OP_PUT, "w", 0, 1, 1.0,
                            np.zeros(4, np.float32))
        finally:
            direct.stop()
        snap = telemetry.snapshot()
        key = f'bf_win_tx_retries_total{{peer="127.0.0.1:{port}"}}'
        assert snap.get(key) == 1.0, snap

        coalesce_env(BLUEFOG_TPU_WIN_COALESCE=1)
        queued = T.WindowTransport(lambda *a: None)
        try:
            queued.send("127.0.0.1", port, T.OP_PUT, "w", 0, 1, 1.0,
                        np.zeros(4, np.float32))  # enqueue: no error yet
            with pytest.raises(ConnectionError):
                queued.flush(timeout=30)
        finally:
            queued.stop()
        assert telemetry.snapshot().get(key) == 2.0
    finally:
        dead.close()


@needs_native
def test_flush_bytes_caps_frame_size(coalesce_env):
    """A backlog larger than BLUEFOG_TPU_WIN_COALESCE_BYTES is shipped as
    MULTIPLE batch frames (bounded encode copies and recv-buffer growth),
    still in order."""
    coalesce_env(BLUEFOG_TPU_WIN_COALESCE=1,
                 BLUEFOG_TPU_WIN_COALESCE_BYTES=8192,
                 BLUEFOG_TPU_WIN_COALESCE_LINGER_MS=20)
    rec = _Recorder()
    server = T.WindowTransport(rec.apply, apply_batch=rec.apply_batch)
    client = T.WindowTransport(lambda *a: None)
    try:
        row = np.zeros(1024, np.float32)  # 4 KB
        for i in range(64):  # 256 KB total vs an 8 KB frame cap
            client.send("127.0.0.1", server.port, T.OP_PUT, "w", i, 0,
                        1.0, row)
        client.flush()
        rec.wait_for(64)
        assert rec.batches >= 8, rec.batches  # many frames, not one blob
        assert [m[2] for m in rec.msgs] == list(range(64))
    finally:
        client.stop()
        server.stop()


@needs_native
def test_error_token_surfaces_failure_to_late_flusher(coalesce_env):
    """A dropped batch can carry several ops' messages but the stored
    per-sender error reaches only the first flusher; flush(since=token)
    raises for every op that overlapped the failure window."""
    import socket
    dead = socket.socket()
    dead.bind(("127.0.0.1", 0))
    port = dead.getsockname()[1]
    coalesce_env(BLUEFOG_TPU_WIN_COALESCE=1)
    t = T.WindowTransport(lambda *a: None)
    try:
        tok = t.error_token()
        t.send("127.0.0.1", port, T.OP_PUT, "w", 0, 1, 1.0,
               np.zeros(4, np.float32))
        with pytest.raises(ConnectionError):  # first flusher: stored error
            t.flush(timeout=30)
        with pytest.raises(ConnectionError):  # late flusher: token catches
            t.flush(timeout=30, since=tok)
        t.flush(timeout=30, since=t.error_token())  # fresh token: clean
    finally:
        t.stop()
        dead.close()


@needs_native
def test_error_token_is_scoped_per_peer(coalesce_env):
    """A failure on one peer's sender must not fail a flush scoped to a
    healthy peer (the legacy behavior: a dead neighbor only stalls ops
    that address it)."""
    import socket
    dead = socket.socket()
    dead.bind(("127.0.0.1", 0))
    dead_port = dead.getsockname()[1]
    coalesce_env(BLUEFOG_TPU_WIN_COALESCE=1)
    rec = _Recorder()
    server = T.WindowTransport(rec.apply, apply_batch=rec.apply_batch)
    client = T.WindowTransport(lambda *a: None)
    live_addr = ("127.0.0.1", server.port)
    try:
        tok = client.error_token({live_addr})
        client.send("127.0.0.1", dead_port, T.OP_PUT, "w", 0, 1, 1.0,
                    np.zeros(4, np.float32))
        client.send(*live_addr, T.OP_PUT, "w", 0, 2, 1.0,
                    np.zeros(4, np.float32))
        # The healthy peer's scoped flush succeeds even while the dead
        # peer's sender records its failure.
        client.flush(timeout=30, addrs={live_addr}, since=tok)
        rec.wait_for(1)
        with pytest.raises(ConnectionError):  # unscoped flush reports it
            client.flush(timeout=30)
    finally:
        client.stop()
        server.stop()
        dead.close()


@needs_native
def test_backpressure_blocks_producer_not_forever(coalesce_env):
    """A tiny per-peer queue bound paces the producer (send blocks until
    the worker drains) instead of dropping gossip."""
    coalesce_env(BLUEFOG_TPU_WIN_COALESCE=1, BLUEFOG_TPU_WIN_TX_QUEUE=4,
                 BLUEFOG_TPU_WIN_COALESCE_LINGER_MS=0)
    rec = _Recorder()
    server = T.WindowTransport(rec.apply, apply_batch=rec.apply_batch)
    client = T.WindowTransport(lambda *a: None)
    try:
        for i in range(64):  # 16x the queue bound
            client.send("127.0.0.1", server.port, T.OP_PUT, "w", i, 0,
                        1.0, np.zeros(16, np.float32))
        client.flush()
        rec.wait_for(64)
        assert [m[2] for m in rec.msgs] == list(range(64))
    finally:
        client.stop()
        server.stop()


# ---------------------------------------------------------------------------
# Vectorized batched apply (window store)
# ---------------------------------------------------------------------------

def _fake_distrib():
    class _T:
        def flush(self, timeout=None):
            pass

        def kick(self):
            pass

        def stop(self):
            pass
    return W._Distrib(_T(), rank_owner={r: 0 for r in range(8)},
                      proc_addr={0: ("127.0.0.1", 1)}, my_proc=0)


def test_batched_apply_matches_sequential_apply():
    """_apply_inbound_batch (grouped, folded, one lock hold) produces the
    same staging / versions / associated-P state as the per-message
    _apply_inbound applied in the same order — including put-then-
    accumulate runs on one slot and interleaved windows."""
    bf.init(lambda: topo.RingGraph(8))
    rng = np.random.RandomState(3)
    x = rng.randn(8, 5).astype(np.float32)
    bf.turn_on_win_ops_with_associated_p()
    try:
        assert bf.win_create(x, "ba", zero_init=True)
        assert bf.win_create(x, "bb", zero_init=True)
        # A message stream exercising fold rules: puts reset, accumulates
        # add, window switches split runs, edges vary.
        msgs = []
        for k in range(40):
            name = "ba" if (k // 7) % 2 == 0 else "bb"
            dst = int(rng.randint(8))
            src = (dst + 1) % 8 if rng.rand() < 0.5 else (dst - 1) % 8
            op = T.OP_PUT if rng.rand() < 0.3 else T.OP_ACCUMULATE
            row = rng.randn(5).astype(np.float32)
            msgs.append((op, name, src, dst, float(rng.rand() + 0.1),
                         float(rng.rand()), row.tobytes()))

        saved = W._store.distrib
        W._store.distrib = _fake_distrib()
        try:
            W._apply_inbound_batch(msgs)
            batched = {n: bf.win_state_dict(n) for n in ("ba", "bb")}
            bf.win_free("ba"), bf.win_free("bb")
            assert bf.win_create(x, "ba", zero_init=True)
            assert bf.win_create(x, "bb", zero_init=True)
            for m in msgs:
                W._apply_inbound(*m)
            sequential = {n: bf.win_state_dict(n) for n in ("ba", "bb")}
        finally:
            W._store.distrib = saved
        for n in ("ba", "bb"):
            for part in ("staging", "versions", "p_staging"):
                for k, v in sequential[n][part].items():
                    got = batched[n][part][k]
                    np.testing.assert_allclose(
                        np.asarray(got), np.asarray(v), rtol=1e-6,
                        atol=1e-6, err_msg=f"{n}.{part}[{k}]")
    finally:
        bf.turn_off_win_ops_with_associated_p()
        bf.win_free("ba")
        bf.win_free("bb")


def test_batched_apply_zero_copy_payloads_are_safe():
    """Feeding memoryviews whose backing buffer is scribbled after the
    call must not corrupt window state (the apply folds rows into fresh
    arrays before returning)."""
    bf.init(lambda: topo.RingGraph(8))
    x = np.zeros((8, 4), np.float32)
    assert bf.win_create(x, "zc", zero_init=True)
    try:
        buf = bytearray(np.full(4, 7.0, np.float32).tobytes())
        msgs = [(T.OP_PUT, "zc", 1, 0, 1.0, 0.0, memoryview(buf))]
        saved = W._store.distrib
        W._store.distrib = _fake_distrib()
        try:
            W._apply_inbound_batch(msgs)
        finally:
            W._store.distrib = saved
        buf[:] = b"\xff" * len(buf)  # transport reuses its recv buffer
        win = W._store.get("zc")
        np.testing.assert_array_equal(win.staging[(0, 1)],
                                      np.full(4, 7.0, np.float32))
    finally:
        bf.win_free("zc")


def test_win_flush_noop_single_process():
    """win_flush is part of the public surface and must be callable (and a
    no-op) without a transport."""
    bf.init(lambda: topo.RingGraph(8))
    bf.win_flush()
    bf.win_flush(wait=False)


@needs_native
def test_batch_frame_through_store_fence_like_sequence(coalesce_env):
    """End-to-end through a real loopback transport INTO the window store:
    puts + accumulates ride one batch frame, the store's batched apply
    lands them, and a trailing fence req is answered only after the data
    was applied (ordering across the transport/store seam)."""
    coalesce_env(BLUEFOG_TPU_WIN_COALESCE=1,
                 BLUEFOG_TPU_WIN_COALESCE_LINGER_MS=5)
    bf.init(lambda: topo.RingGraph(8))
    x = np.zeros((8, 3), np.float32)
    assert bf.win_create(x, "e2e", zero_init=True)
    applied_before_fence = []
    fence_seen = threading.Event()

    def apply(op, name, src, dst, weight, p_weight, payload):
        if (op & ~T.OP_BF16_FLAG) == T.OP_FENCE_REQ:
            win = W._store.get("e2e")
            with win.lock:
                applied_before_fence.append(win.versions[(0, 1)])
            fence_seen.set()
            return
        W._apply_inbound(op, name, src, dst, weight, p_weight, payload)

    def apply_batch(msgs):
        for m in msgs:
            apply(*m)

    server = T.WindowTransport(apply, apply_batch=apply_batch)
    client = T.WindowTransport(lambda *a: None)
    saved = W._store.distrib
    W._store.distrib = _fake_distrib()
    try:
        row = np.arange(3, dtype=np.float32)
        for _ in range(5):
            client.send("127.0.0.1", server.port, T.OP_ACCUMULATE, "e2e",
                        1, 0, 1.0, row)
        client.send("127.0.0.1", server.port, T.OP_FENCE_REQ, "", 1, -1,
                    0.0, np.zeros(0, np.float32))
        client.flush()
        assert fence_seen.wait(timeout=20)
        # All 5 accumulates were applied BEFORE the fence was serviced.
        assert applied_before_fence == [5]
        win = W._store.get("e2e")
        np.testing.assert_allclose(win.staging[(0, 1)], 5 * row)
    finally:
        W._store.distrib = saved
        client.stop()
        server.stop()
        bf.win_free("e2e")


# ---------------------------------------------------------------------------
# Retry policy knobs + peer restart recovery (churn PR satellites)
# ---------------------------------------------------------------------------

@needs_native
def test_retry_knobs_env(coalesce_env):
    """BLUEFOG_TPU_WIN_RETRIES / BLUEFOG_TPU_WIN_RETRY_BACKOFF_MS: 0 fails
    fast with no retry counted; 3 counts exactly three attempts in
    bf_win_tx_retries_total."""
    import socket
    dead = socket.socket()
    dead.bind(("127.0.0.1", 0))  # bound, never listening: connect refused
    port = dead.getsockname()[1]
    key = f'bf_win_tx_retries_total{{peer="127.0.0.1:{port}"}}'
    telemetry.reset()
    try:
        coalesce_env(BLUEFOG_TPU_WIN_COALESCE=0, BLUEFOG_TPU_WIN_RETRIES=0)
        t = T.WindowTransport(lambda *a: None)
        try:
            with pytest.raises(ConnectionError):
                t.send("127.0.0.1", port, T.OP_PUT, "w", 0, 1, 1.0,
                       np.zeros(4, np.float32))
        finally:
            t.stop()
        assert key not in telemetry.snapshot()

        coalesce_env(BLUEFOG_TPU_WIN_COALESCE=0, BLUEFOG_TPU_WIN_RETRIES=3,
                     BLUEFOG_TPU_WIN_RETRY_BACKOFF_MS=1)
        t = T.WindowTransport(lambda *a: None)
        try:
            with pytest.raises(ConnectionError):
                t.send("127.0.0.1", port, T.OP_PUT, "w", 0, 1, 1.0,
                       np.zeros(4, np.float32))
        finally:
            t.stop()
        assert telemetry.snapshot().get(key) == 3.0
    finally:
        dead.close()


@needs_native
def test_peer_restart_scoped_failure_then_fresh_traffic(coalesce_env):
    """The churn recovery contract at the transport layer: a dead peer
    fails ONLY the overlapped ops that addressed it (the per-peer
    error-epoch token never fails a healthy peer's flush), and once the
    peer (re)starts ON THE SAME PORT the same client transport serves
    fresh traffic to it — no client-side rebuild.

    The dead peer is a bound-but-never-listening socket (deterministic
    connect-refused); a peer that dies with an ESTABLISHED connection can
    absorb one in-flight write into the kernel buffer before the RST
    surfaces — that loss window is exactly why the churn controller
    detects death by heartbeat + probe, never by send errors alone."""
    import socket
    coalesce_env(BLUEFOG_TPU_WIN_COALESCE=1, BLUEFOG_TPU_WIN_RETRIES=1,
                 BLUEFOG_TPU_WIN_RETRY_BACKOFF_MS=5,
                 BLUEFOG_TPU_WIN_COALESCE_LINGER_MS=1)
    rec_a = _Recorder()
    srv_a = T.WindowTransport(rec_a.apply, apply_batch=rec_a.apply_batch)
    dead = socket.socket()
    dead.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    dead.bind(("127.0.0.1", 0))  # bound, never listening: connect refused
    port_b = dead.getsockname()[1]
    client = T.WindowTransport(lambda *a: None)
    addr_a = ("127.0.0.1", srv_a.port)
    addr_b = ("127.0.0.1", port_b)
    srv_b = None
    try:
        row = np.arange(4, dtype=np.float32)
        tok_a = client.error_token({addr_a})
        tok_b = client.error_token({addr_b})
        client.send(*addr_b, T.OP_PUT, "w", 0, 2, 1.0, row)
        client.send(*addr_a, T.OP_PUT, "w", 0, 1, 1.0, row)
        # The op that addressed the dead peer fails...
        with pytest.raises(ConnectionError):
            client.flush(timeout=30, addrs={addr_b}, since=tok_b)
        # ...while the op that addressed the healthy peer is untouched,
        # even though the failure happened inside its overlap window.
        client.flush(timeout=30, addrs={addr_a}, since=tok_a)
        rec_a.wait_for(1)

        # The peer comes up on the SAME port (restart): fresh traffic
        # must flow through the surviving client transport immediately.
        dead.close()
        rec_b = _Recorder()
        srv_b = T.WindowTransport(rec_b.apply,
                                  apply_batch=rec_b.apply_batch,
                                  port=port_b)
        client.send(*addr_b, T.OP_PUT, "w", 0, 2, 7.0, row)
        client.flush(timeout=30, addrs={addr_b},
                     since=client.error_token({addr_b}))
        rec_b.wait_for(1)
        assert rec_b.msgs[0][4] == 7.0  # the post-restart message, intact
    finally:
        client.stop()
        srv_a.stop()
        if srv_b is not None:
            srv_b.stop()
        try:
            dead.close()
        except OSError:
            pass


@needs_native
def test_drop_peer_discards_queue_and_allows_lazy_recreate(coalesce_env):
    """drop_peer (churn: the peer is dead by consensus) retires the sender
    without stalling: queued messages are discarded and counted, flush no
    longer waits on the dead peer, and a LATER send to the same address
    lazily builds a fresh sender (peer restart)."""
    import socket
    dead = socket.socket()
    dead.bind(("127.0.0.1", 0))
    port = dead.getsockname()[1]
    coalesce_env(BLUEFOG_TPU_WIN_COALESCE=1, BLUEFOG_TPU_WIN_RETRIES=0,
                 BLUEFOG_TPU_WIN_COALESCE_LINGER_MS=500)
    t = T.WindowTransport(lambda *a: None)
    try:
        # Long linger: the message sits queued when drop_peer fires.
        t.send("127.0.0.1", port, T.OP_PUT, "w", 0, 1, 1.0,
               np.zeros(4, np.float32))
        t.drop_peer("127.0.0.1", port)
        t.flush(timeout=5)  # dead peer's queue is gone: nothing to wait on
        # A fresh send lazily recreates the sender (restart path) on BOTH
        # hot paths — and the fresh sender really processes the message:
        # the still-dead endpoint surfaces again at the next flush.
        t.send("127.0.0.1", port, T.OP_PUT, "w", 0, 1, 1.0,
               np.zeros(4, np.float32))
        if t.native_path:
            with pytest.raises(ConnectionError):
                t.flush(timeout=10)
        else:
            with t._senders_lock:
                assert ("127.0.0.1", port) in t._senders
    finally:
        t.stop()
        dead.close()


@needs_native
def test_set_partition_drops_sends_and_heals(coalesce_env):
    """Chaos partition: sends to partitioned peers fail like a dead link
    (no wire traffic, no retries); healing restores delivery."""
    coalesce_env(BLUEFOG_TPU_WIN_COALESCE=1, BLUEFOG_TPU_WIN_RETRIES=2,
                 BLUEFOG_TPU_WIN_RETRY_BACKOFF_MS=50)
    rec = _Recorder()
    server = T.WindowTransport(rec.apply, apply_batch=rec.apply_batch)
    client = T.WindowTransport(lambda *a: None)
    addr = ("127.0.0.1", server.port)
    key = f'bf_win_tx_retries_total{{peer="127.0.0.1:{server.port}"}}'
    telemetry.reset()
    try:
        client.set_partition({addr})
        client.send(*addr, T.OP_PUT, "w", 0, 1, 1.0,
                    np.zeros(4, np.float32))
        with pytest.raises(ConnectionError):
            client.flush(timeout=30)
        assert key not in telemetry.snapshot()  # partition never retries
        client.set_partition(None)
        client.send(*addr, T.OP_PUT, "w", 0, 1, 1.0,
                    np.zeros(4, np.float32))
        client.flush(timeout=30)
        rec.wait_for(1)
    finally:
        client.stop()
        server.stop()


@needs_native
def test_drop_peer_fails_blocked_flusher_immediately(coalesce_env):
    """A producer already blocked in flush() on the dead peer must fail
    the moment drop_peer retires it — not wait out the closing grace for
    messages that can never be handed to TCP (the churn supervisor's
    recovery latency depends on this)."""
    import socket
    import time as _time
    dead = socket.socket()
    dead.bind(("127.0.0.1", 0))
    port = dead.getsockname()[1]
    coalesce_env(BLUEFOG_TPU_WIN_COALESCE=1, BLUEFOG_TPU_WIN_RETRIES=0,
                 BLUEFOG_TPU_WIN_COALESCE_LINGER_MS=50)
    t = T.WindowTransport(lambda *a: None)
    outcome = []

    def flusher():
        t0 = _time.perf_counter()
        try:
            t.flush(timeout=30)
            outcome.append(("ok", _time.perf_counter() - t0))
        except ConnectionError:
            outcome.append(("err", _time.perf_counter() - t0))

    try:
        t.send("127.0.0.1", port, T.OP_PUT, "w", 0, 1, 1.0,
               np.zeros(4, np.float32))
        th = threading.Thread(target=flusher)
        th.start()
        _time.sleep(0.2)
        t.drop_peer("127.0.0.1", port)
        th.join(timeout=10)
        assert not th.is_alive()
        # Raised (either from the worker's own fast connect failure or
        # from the drop itself) well inside the 5 s closing grace.
        assert outcome and outcome[0][0] == "err"
        assert outcome[0][1] < 3.0, outcome
    finally:
        t.stop()
        dead.close()


# ---------------------------------------------------------------------------
# Native hot path (BLUEFOG_TPU_WIN_NATIVE): cross-codec equivalence oracle
# ---------------------------------------------------------------------------

needs_win_native = pytest.mark.skipif(
    not native.available() or not native.has_win_native(),
    reason="native window hot path not built")


def _mixed_stream(seed, count):
    """A deterministic mixed-op message stream: dense/bf16/sparse data
    payloads, zero-length fence/mutex control ops, awkward names."""
    rng = np.random.RandomState(seed)
    names = ["w", "a.b/c:d", "x" * 127]
    msgs = []
    for _ in range(count):
        roll = rng.rand()
        if roll < 0.5:
            op = T.OP_PUT if rng.rand() < 0.4 else T.OP_ACCUMULATE
            row = rng.randn(6).astype(np.float32)
            kind = rng.rand()
            if kind < 0.2:
                op |= T.OP_BF16_FLAG
                import jax.numpy as jnp
                payload = np.asarray(row, dtype=np.dtype(jnp.bfloat16))
            elif kind < 0.4:
                op |= T.OP_SPARSE_FLAG
                idx = np.sort(rng.choice(6, size=3, replace=False))
                payload = T.sparse_encode(row[idx].astype(np.float32),
                                          idx.astype(np.int32))
            else:
                payload = row
            msgs.append((op, str(rng.choice(names)), int(rng.randint(8)),
                         int(rng.randint(8)), float(rng.rand() + 0.1),
                         float(rng.rand()), np.ascontiguousarray(payload)))
        else:
            op = int(rng.choice([T.OP_FENCE_REQ, T.OP_MUTEX_ACQ,
                                 T.OP_MUTEX_REL, T.OP_GET_REQ]))
            msgs.append((op, str(rng.choice(names)), int(rng.randint(8)),
                         int(rng.randint(8)), 0.0, 0.0,
                         np.zeros(0, np.float32)))
    return msgs


def _loopback_capture(coalesce_env, client_native, server_native, msgs):
    """Ship ``msgs`` through a loopback pair with the requested path on
    each side; returns the (op, name, src, dst, w, pw, payload-bytes)
    tuples the server decoded, in arrival order."""
    rec = _Recorder()

    def apply_items(items):
        for kind, payload in items:
            assert kind == 0, "no windows registered: commits impossible"
            rec.apply(*payload)

    coalesce_env(BLUEFOG_TPU_WIN_COALESCE=1,
                 BLUEFOG_TPU_WIN_COALESCE_LINGER_MS=200,
                 BLUEFOG_TPU_WIN_NATIVE=1 if server_native else 0)
    server = T.WindowTransport(rec.apply, apply_batch=rec.apply_batch,
                               apply_items=apply_items)
    coalesce_env(BLUEFOG_TPU_WIN_COALESCE=1,
                 BLUEFOG_TPU_WIN_COALESCE_LINGER_MS=200,
                 BLUEFOG_TPU_WIN_NATIVE=1 if client_native else 0)
    client = T.WindowTransport(lambda *a: None)
    try:
        assert server.native_path == server_native
        assert client.native_path == client_native
        for (op, name, src, dst, w, pw, payload) in msgs:
            client.send("127.0.0.1", server.port, op, name, src, dst, w,
                        payload, p_weight=pw)
        client.flush()
        rec.wait_for(len(msgs))
        return list(rec.msgs)
    finally:
        client.stop()
        server.stop()


@needs_win_native
def test_native_encoder_decodes_bit_identically_by_python_and_vice_versa(
        coalesce_env):
    """Cross-codec property test: every frame the NATIVE encoder ships is
    decoded bit-identically by the PYTHON decoder, and every frame the
    Python encoder ships is decoded bit-identically by the NATIVE drain
    (unregistered windows -> raw items) — mixed ops, OP_BF16_FLAG,
    OP_SPARSE_FLAG, zero-length fence/mutex payloads, order preserved."""
    msgs = _mixed_stream(seed=7, count=120)
    for client_native, server_native in ((True, False), (False, True),
                                         (True, True)):
        got = _loopback_capture(coalesce_env, client_native, server_native,
                                msgs)
        assert len(got) == len(msgs), (client_native, server_native)
        for sent, rx in zip(msgs, got):
            assert sent[:6] == rx[:6], (client_native, server_native)
            assert np.ascontiguousarray(sent[6]).tobytes() == rx[6], \
                (client_native, server_native)


@needs_win_native
def test_native_send_rejects_long_name_with_valueerror(coalesce_env):
    """bf_wintx_send rc=-4 (name over the receiver's 128-byte field)
    surfaces as a ValueError naming the limit — a deterministic caller
    bug, not a ConnectionError to retry."""
    coalesce_env(BLUEFOG_TPU_WIN_COALESCE=1, BLUEFOG_TPU_WIN_NATIVE=1)
    t = T.WindowTransport(lambda *a: None)
    try:
        assert t.native_path
        with pytest.raises(ValueError, match="128"):
            t.send("127.0.0.1", t.port, T.OP_PUT, "n" * 200, 0, 1, 1.0,
                   np.zeros(4, np.float32))
    finally:
        t.stop()


def _drive_store_stream(coalesce_env, use_native, with_p):
    """Run one deterministic put/accumulate stream through a REAL loopback
    transport into the window store (batched frames, controlled framing:
    one flush per message group so both paths fold identical groups) and
    snapshot the resulting window state."""
    import bluefog_tpu as bf
    from bluefog_tpu import topology as topo

    bf.init(lambda: topo.RingGraph(8))
    rng = np.random.RandomState(11)
    x = rng.randn(8, 5).astype(np.float32)
    if with_p:
        bf.turn_on_win_ops_with_associated_p()
    coalesce_env(BLUEFOG_TPU_WIN_COALESCE=1,
                 BLUEFOG_TPU_WIN_COALESCE_LINGER_MS=500,
                 BLUEFOG_TPU_WIN_NATIVE=1 if use_native else 0)
    applied = [0]
    cv = threading.Condition()

    def apply(op, name, src, dst, weight, p_weight, payload):
        W._apply_inbound(op, name, src, dst, weight, p_weight, payload)
        with cv:
            applied[0] += 1
            cv.notify_all()

    def apply_batch(msgs):
        W._apply_inbound_batch(msgs)
        with cv:
            applied[0] += len(msgs)
            cv.notify_all()

    def apply_items(items):
        W._apply_inbound_items(items)
        with cv:
            applied[0] += sum((p[5] + p[6]) if k else 1 for k, p in items)
            cv.notify_all()

    server = T.WindowTransport(apply, apply_batch=apply_batch,
                               apply_items=apply_items)
    client = T.WindowTransport(lambda *a: None)
    saved = W._store.distrib
    W._store.distrib = _fake_distrib()
    try:
        assert server.native_path == use_native
        assert bf.win_create(x, "eqa", zero_init=True)
        assert bf.win_create(x, "eqb", zero_init=True)
        for n in ("eqa", "eqb"):
            server.register_window(n, 5)
        # Deterministic stream: groups of puts/accumulates (same-slot
        # folds, window switches, bf16 + sparse codec edges), one flush
        # per group => identical frame boundaries on both paths.
        total = 0
        for g in range(12):
            grng = np.random.RandomState(100 + g)
            for k in range(6):
                name = "eqa" if (g + k) % 3 else "eqb"
                dst = int(grng.randint(8))
                src = (dst + 1) % 8 if grng.rand() < 0.5 else (dst - 1) % 8
                op = T.OP_PUT if grng.rand() < 0.3 else T.OP_ACCUMULATE
                row = grng.randn(5).astype(np.float32)
                payload = row
                roll = grng.rand()
                if roll < 0.25 and op == T.OP_ACCUMULATE:
                    idx = np.sort(grng.choice(5, size=2, replace=False))
                    payload = T.sparse_encode(
                        row[idx].astype(np.float32), idx.astype(np.int32))
                    op |= T.OP_SPARSE_FLAG
                elif roll < 0.5:
                    import jax.numpy as jnp
                    payload = np.asarray(row,
                                         dtype=np.dtype(jnp.bfloat16))
                    op |= T.OP_BF16_FLAG
                client.send("127.0.0.1", server.port, op, name, src, dst,
                            float(grng.rand() + 0.1), payload,
                            p_weight=float(grng.rand()))
                total += 1
            client.flush()
        with cv:
            assert cv.wait_for(lambda: applied[0] >= total, timeout=30), \
                (applied[0], total)
        return {n: bf.win_state_dict(n) for n in ("eqa", "eqb")}
    finally:
        W._store.distrib = saved
        client.stop()
        server.stop()
        bf.win_free("eqa")
        bf.win_free("eqb")
        if with_p:
            bf.turn_off_win_ops_with_associated_p()


@needs_win_native
@pytest.mark.parametrize("with_p", [False, True])
def test_native_vs_python_drain_state_equivalence_bitwise(coalesce_env,
                                                          with_p):
    """The BLUEFOG_TPU_WIN_NATIVE=0/1 end-to-end oracle: the SAME wire
    stream (real loopback frames, controlled framing) lands BIT-IDENTICAL
    window state — staging rows, version counters, associated-P — whether
    the drain decode+fold ran in C++ or in Python."""
    nat = _drive_store_stream(coalesce_env, use_native=True, with_p=with_p)
    py = _drive_store_stream(coalesce_env, use_native=False, with_p=with_p)
    for n in ("eqa", "eqb"):
        for part in ("staging", "versions", "p_staging"):
            assert set(py[n][part]) == set(nat[n][part]), (n, part)
            for k, v in py[n][part].items():
                np.testing.assert_array_equal(
                    np.asarray(nat[n][part][k]), np.asarray(v),
                    err_msg=f"{n}.{part}[{k}] (bitwise)")


@needs_win_native
def test_native_fold_counts_versions_and_batches(coalesce_env):
    """Folded runs keep the per-message version ticks (3 accumulates into
    one slot = one commit entry, +3 on the version counter) and the
    native counters flow into the telemetry registry."""
    import bluefog_tpu as bf
    from bluefog_tpu import topology as topo
    bf.init(lambda: topo.RingGraph(8))
    coalesce_env(BLUEFOG_TPU_WIN_COALESCE=1,
                 BLUEFOG_TPU_WIN_COALESCE_LINGER_MS=500,
                 BLUEFOG_TPU_WIN_NATIVE=1)
    telemetry.reset()
    x = np.zeros((8, 4), np.float32)
    done = threading.Event()

    def apply_items(items):
        W._apply_inbound_items(items)
        done.set()

    server = T.WindowTransport(W._apply_inbound,
                               apply_batch=W._apply_inbound_batch,
                               apply_items=apply_items)
    client = T.WindowTransport(lambda *a: None)
    saved = W._store.distrib
    W._store.distrib = _fake_distrib()
    try:
        assert server.native_path
        assert bf.win_create(x, "fold", zero_init=True)
        server.register_window("fold", 4)
        row = np.arange(4, dtype=np.float32)
        for _ in range(3):
            client.send("127.0.0.1", server.port, T.OP_ACCUMULATE, "fold",
                        1, 0, 2.0, row)
        client.flush()
        assert done.wait(timeout=20)
        win = W._store.get("fold")
        assert win.versions[(0, 1)] == 3
        np.testing.assert_array_equal(win.staging[(0, 1)], 6 * row)
        client.stop()
        server.stop()
        snap = telemetry.snapshot()
        assert snap.get("bf_win_native_tx_frames_total", 0) > 0
        assert snap.get("bf_win_native_rx_frames_total", 0) > 0
        assert snap.get("bf_win_native_rx_commits_total", 0) >= 1
        assert snap.get("bf_win_native_rx_folded_msgs_total", 0) >= 3
    finally:
        W._store.distrib = saved
        try:
            client.stop()
            server.stop()
        except Exception:
            pass
        import bluefog_tpu as bf2
        bf2.win_free("fold")
