"""Input-pipeline tests: rank partitioning, epoch shuffling, prefetch
placement (parity: the torch DistributedSampler contract the reference's
examples rely on, ``examples/pytorch_mnist.py:100-120``)."""

import numpy as np
import pytest

import jax
import bluefog_tpu as bf
from bluefog_tpu.data import DistributedSampler, ShardedLoader, \
    prefetch_to_device


@pytest.fixture(autouse=True)
def _init():
    if not bf.initialized():
        bf.init()
    yield


def test_sampler_partitions_disjoint_and_complete():
    s = DistributedSampler(64, num_ranks=8, shuffle=True, seed=3)
    idx = s.indices()
    assert idx.shape == (8, 8)
    flat = np.sort(idx.ravel())
    np.testing.assert_array_equal(flat, np.arange(64))  # disjoint + complete


def test_sampler_epoch_reshuffles_deterministically():
    s = DistributedSampler(40, num_ranks=4, seed=7)
    a = s.indices()
    s.set_epoch(1)
    b = s.indices()
    assert not np.array_equal(a, b)
    s2 = DistributedSampler(40, num_ranks=4, seed=7)
    s2.set_epoch(1)
    np.testing.assert_array_equal(b, s2.indices())  # same everywhere


def test_sampler_drop_last_vs_wrap():
    dropped = DistributedSampler(30, num_ranks=4, drop_last=True)
    assert dropped.per_rank == 7
    wrapped = DistributedSampler(30, num_ranks=4, drop_last=False,
                                 shuffle=False)
    assert wrapped.per_rank == 8
    idx = wrapped.indices()
    # wrap-pad: every sample present at least once, 2 duplicates total
    assert idx.size == 32
    np.testing.assert_array_equal(np.unique(idx), np.arange(30))


def test_sharded_loader_shapes_and_sharding():
    n = bf.size()
    x = np.arange(n * 6 * 3, dtype=np.float32).reshape(n * 6, 3)
    y = np.arange(n * 6, dtype=np.int32)
    loader = ShardedLoader({"x": x, "y": y}, batch_size=2, shuffle=False)
    assert loader.steps_per_epoch == 3 and len(loader) == 3
    batches = list(loader)
    assert len(batches) == 3
    b0 = batches[0]
    assert b0["x"].shape == (n, 2, 3) and b0["y"].shape == (n, 2)
    assert isinstance(b0["x"], jax.Array)
    # placed with the rank-major sharding: row r on device r
    assert b0["x"].sharding.is_equivalent_to(
        bf.basics._rank_sharding(), ndim=3)
    # unshuffled: rank r's first batch rows are its shard's first samples
    got = np.asarray(b0["y"])
    np.testing.assert_array_equal(
        got, np.arange(n * 6).reshape(n, 6)[:, :2])


def test_sharded_loader_epoch_coverage():
    n = bf.size()
    y = np.arange(n * 4, dtype=np.int64)
    loader = ShardedLoader({"y": y}, batch_size=2, seed=11)
    seen = np.concatenate(
        [np.asarray(b["y"]).ravel() for b in loader])
    np.testing.assert_array_equal(np.sort(seen), y)  # every sample, once


def test_sharded_loader_transform_runs_off_thread():
    n = bf.size()
    x = np.ones((n * 2, 2), np.float32)

    def tf(batch):
        return {"x": batch["x"] * 3.0}

    loader = ShardedLoader({"x": x}, batch_size=2, transform=tf)
    (batch,) = list(loader)
    np.testing.assert_allclose(np.asarray(batch["x"]), 3.0)


def test_prefetch_propagates_errors():
    def gen():
        yield np.zeros((bf.size(), 1), np.float32)
        raise RuntimeError("boom")

    it = prefetch_to_device(gen())
    next(it)
    with pytest.raises(RuntimeError, match="boom"):
        next(it)


def test_prefetch_raw_numpy_mode():
    batches = [np.zeros((2, 2)), np.ones((2, 2))]
    out = list(prefetch_to_device(iter(batches), sharding=False))
    assert len(out) == 2 and isinstance(out[0], np.ndarray)


def test_sampler_too_few_samples_raises():
    with pytest.raises(ValueError, match="cannot shard"):
        DistributedSampler(3, num_ranks=8)


def test_static_shards_fix_membership_across_epochs():
    s = DistributedSampler(32, num_ranks=4, static_shards=True, seed=5)
    e0 = s.indices()
    s.set_epoch(1)
    e1 = s.indices()
    for r in range(4):  # same members every epoch (decentralized-DP)...
        np.testing.assert_array_equal(np.sort(e0[r]), np.arange(8 * r, 8 * r + 8))
        np.testing.assert_array_equal(np.sort(e1[r]), np.sort(e0[r]))
    assert not np.array_equal(e0, e1)  # ...but shuffled within the shard


def test_loader_drop_last_false_trains_every_sample():
    """drop_last=False must not silently drop the tail: batches wrap-pad so
    each of the 30 samples appears at least once per epoch."""
    y = np.arange(30, dtype=np.int64)
    loader = ShardedLoader({"y": y}, batch_size=3, num_ranks=4,
                           drop_last=False, seed=2, sharding=False)
    assert loader.steps_per_epoch == 3  # ceil(8 / 3)
    seen = np.concatenate([np.asarray(b["y"]).ravel() for b in loader])
    assert seen.size == 4 * 3 * 3
    np.testing.assert_array_equal(np.unique(seen), np.arange(30))
    # constant shapes throughout (SPMD requirement)
    for b in loader:
        assert b["y"].shape == (4, 3)


def test_prefetch_abandoned_consumer_releases_producer():
    """Breaking out of a training loop mid-epoch must not leak the prefetch
    thread blocked on the bounded queue."""
    import threading
    import time

    produced = []

    def gen():
        for i in range(100):
            produced.append(i)
            yield np.zeros((2, 2))

    it = prefetch_to_device(gen(), size=1, sharding=False)
    next(it)
    it.close()  # abandon (same path as `break` + GC of the generator)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if not any(t.name == "bf-data-prefetch" and t.is_alive()
                   for t in threading.enumerate()):
            break
        time.sleep(0.05)
    assert not any(t.name == "bf-data-prefetch" and t.is_alive()
                   for t in threading.enumerate()), "producer thread leaked"
    assert len(produced) < 100  # it stopped early, not after exhausting gen
