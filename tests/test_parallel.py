"""Sequence-parallel attention tests: ring / Ulysses vs dense reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from bluefog_tpu.models.transformer import local_attention
from bluefog_tpu.parallel import ring_attention, ulysses_attention

B, S, H, D = 2, 32, 8, 16
NDEV = 8


@pytest.fixture
def qkv():
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    return mk(), mk(), mk()


def seq_sharded(fn, devices):
    # check_vma=False: the ring path calls Pallas kernels which on CPU run
    # under the interpreter, where in-kernel constants are not vma-tracked
    # (compiled Mosaic kernels on TPU work under check_vma=True).
    mesh = Mesh(np.asarray(devices), ("sp",))
    return jax.jit(jax.shard_map(
        fn, mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"), check_vma=False))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(devices, qkv, causal):
    q, k, v = qkv
    ref = local_attention(q, k, v, causal=causal)
    out = seq_sharded(
        lambda a, b, c: ring_attention(a, b, c, axis_name="sp", causal=causal),
        devices)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_dense(devices, qkv, causal):
    q, k, v = qkv
    ref = local_attention(q, k, v, causal=causal)
    out = seq_sharded(
        lambda a, b, c: ulysses_attention(a, b, c, axis_name="sp",
                                          causal=causal),
        devices)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_ring_attention_grad_matches_dense(devices, qkv):
    """Differentiability: ring attention must backprop like dense."""
    q, k, v = qkv

    def loss_dense(q, k, v):
        return jnp.sum(local_attention(q, k, v, causal=True) ** 2)

    def loss_ring(q, k, v):
        mesh = Mesh(np.asarray(devices), ("sp",))
        out = jax.shard_map(
            lambda a, b, c: ring_attention(a, b, c, axis_name="sp"),
            mesh=mesh, in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"), check_vma=False)(q, k, v)
        return jnp.sum(out ** 2)

    g_ref = jax.grad(loss_dense)(q, k, v)
    g_ring = jax.jit(jax.grad(loss_ring))(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                               rtol=5e-3, atol=5e-3)


@pytest.mark.slow
def test_transformer_with_ring_attention(devices):
    """End-to-end: TransformerLM forward with sequence-parallel attention
    equals the single-device model."""
    from bluefog_tpu.models import TransformerLM, TransformerConfig
    from bluefog_tpu.parallel import ring_attention_impl

    cfg = TransformerConfig(vocab_size=128, num_layers=2, num_heads=4,
                            embed_dim=64, max_seq_len=64, dtype=jnp.float32)
    tokens = jnp.asarray(np.random.RandomState(1).randint(0, 128, (2, 64)))
    model_ref = TransformerLM(cfg)
    params = model_ref.init(jax.random.PRNGKey(0), tokens)
    ref = model_ref.apply(params, tokens)

    mesh = Mesh(np.asarray(devices), ("sp",))
    model_sp = TransformerLM(cfg, attn_impl=ring_attention_impl("sp"))
    positions = jnp.arange(64)[None, :].repeat(2, axis=0)

    def fwd(tokens, positions):
        return model_sp.apply(params, tokens, positions=positions)

    out = jax.jit(jax.shard_map(
        fwd, mesh=mesh, in_specs=(P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"), check_vma=False))(tokens, positions)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_ring_flash_compiled_on_tpu_default_vma():
    """Compiled Mosaic path: ring_attention (flash inner kernel) inside a
    shard_map with the DEFAULT check_vma=True — exercises the vma threading
    through the kernels' out_shapes.  Clean subprocess (the suite pins CPU);
    skipped when no TPU is attached."""
    import os
    import subprocess
    import sys
    from conftest import tpu_subprocess_env
    env = tpu_subprocess_env()  # skip on outage/no-TPU, FAIL on broken env
    probe = """
import jax, jax.numpy as jnp, numpy as np, sys
if jax.default_backend() != "tpu":
    print("NO-TPU"); sys.exit(0)
from jax.sharding import Mesh, PartitionSpec as P
from bluefog_tpu.parallel.ring_attention import ring_attention
from bluefog_tpu.models import local_attention
# ALL visible chips: on a pod this compiles the true multi-hop ring (switch over
# Pallas branches, ppermute, vma threading); this sandbox has one chip, where
# only the diagonal hop executes — still the compiled-under-check_vma path.
ndev = len(jax.devices())
B, S, H, D = 1, 256 * ndev, 4, 64  # per-device chunk stays 256 rows
rng = np.random.RandomState(0)
q, k, v = (jnp.asarray(rng.randn(B, S, H, D), jnp.bfloat16) for _ in range(3))
mesh = Mesh(np.asarray(jax.devices()), ("sp",))
f = jax.jit(jax.shard_map(
    lambda a, b, c: ring_attention(a, b, c, axis_name="sp", causal=True),
    mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp")))
out = f(q, k, v)
ref = local_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                      v.astype(jnp.float32), causal=True)
err = float(jnp.abs(out.astype(jnp.float32) - ref).max())
assert err < 0.05, err
print("RING-VMA-OK", err)
"""
    out = subprocess.run([sys.executable, "-c", probe], env=env,
                         capture_output=True, text=True, timeout=560)
    assert out.returncode == 0, f"stdout={out.stdout}\nstderr={out.stderr}"
    if "NO-TPU" in out.stdout:
        pytest.skip("no TPU attached")
    assert "RING-VMA-OK" in out.stdout, out.stdout


def test_tensor_parallel_sharded_forward_matches(devices):
    """Megatron-layout TP via GSPMD: the sharded forward equals the
    single-device forward, with XLA placing the collectives."""
    from jax.sharding import NamedSharding
    from bluefog_tpu.models import TransformerLM, TransformerConfig
    from bluefog_tpu.parallel.tensor_parallel import (tp_param_specs,
                                                      tp_shard_params)

    cfg = TransformerConfig(vocab_size=128, num_layers=2, num_heads=4,
                            embed_dim=32, max_seq_len=16, dtype=jnp.float32)
    model = TransformerLM(cfg)
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 128, (4, 16)))
    params = model.init(jax.random.PRNGKey(0), tokens)
    ref = model.apply(params, tokens)

    mesh = Mesh(np.asarray(devices).reshape(2, 4), ("dp", "tp"))
    specs = tp_param_specs(params, axis="tp")
    # every block kernel got a sharded spec
    flat = jax.tree_util.tree_flatten_with_path(specs)[0]
    sharded = [p for p, s in flat if s != P()]
    assert len(sharded) >= 2 * 4 + 1, flat  # 4 kernels/block x 2 + lm_head
    p_sh = tp_shard_params(params, mesh, axis="tp")
    t_sh = jax.device_put(tokens, NamedSharding(mesh, P("dp")))
    out = jax.jit(model.apply)(p_sh, t_sh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_tensor_parallel_gqa_sharded_forward_matches(devices):
    """GQA's separate q/kv projections get column-parallel specs and the
    sharded forward still equals the single-device one."""
    from jax.sharding import NamedSharding
    from bluefog_tpu.models import TransformerLM, TransformerConfig
    from bluefog_tpu.parallel.tensor_parallel import (tp_param_specs,
                                                      tp_shard_params)

    cfg = TransformerConfig(vocab_size=128, num_layers=2, num_heads=4,
                            num_kv_heads=2, embed_dim=32, max_seq_len=16,
                            dtype=jnp.float32)
    model = TransformerLM(cfg)
    tokens = jnp.asarray(np.random.RandomState(1).randint(0, 128, (4, 16)))
    params = model.init(jax.random.PRNGKey(0), tokens)
    ref = model.apply(params, tokens)

    specs = tp_param_specs(params, axis="tp")
    flat = {"/".join(str(getattr(k, "key", k)) for k in path): s
            for path, s in jax.tree_util.tree_flatten_with_path(specs)[0]}
    assert flat["params/block_0/q/kernel"] == P(None, "tp")
    assert flat["params/block_0/kv/kernel"] == P(None, "tp")

    mesh = Mesh(np.asarray(devices).reshape(2, 4), ("dp", "tp"))
    p_sh = tp_shard_params(params, mesh, axis="tp")
    t_sh = jax.device_put(tokens, NamedSharding(mesh, P("dp")))
    out = jax.jit(model.apply)(p_sh, t_sh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_tensor_parallel_grad_step_matches(devices):
    """TP + batch-DP sharded loss/grad equals the unsharded computation —
    one jit, layouts only."""
    from jax.sharding import NamedSharding
    from bluefog_tpu.models import TransformerLM, TransformerConfig
    from bluefog_tpu.parallel.tensor_parallel import tp_shard_params

    cfg = TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                            embed_dim=32, max_seq_len=16, dtype=jnp.float32)
    model = TransformerLM(cfg)
    tokens = jnp.asarray(np.random.RandomState(1).randint(0, 64, (4, 16)))
    params = model.init(jax.random.PRNGKey(0), tokens)

    def loss(p, t):
        logits = model.apply(p, t)
        tgt = jnp.roll(t, -1, axis=1)
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, tgt[..., None], -1).mean()

    ref_loss, ref_grads = jax.value_and_grad(loss)(params, tokens)

    mesh = Mesh(np.asarray(devices).reshape(2, 4), ("dp", "tp"))
    p_sh = tp_shard_params(params, mesh)
    t_sh = jax.device_put(tokens, NamedSharding(mesh, P("dp")))
    out_loss, out_grads = jax.jit(jax.value_and_grad(loss))(p_sh, t_sh)
    np.testing.assert_allclose(float(out_loss), float(ref_loss), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(ref_grads),
                    jax.tree_util.tree_leaves(out_grads)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-4, atol=1e-5)


def _mlp_stage(w, x):
    return jnp.tanh(x @ w)


def test_pipeline_matches_sequential(devices):
    """GPipe schedule over 4 stages: outputs equal applying the stages
    sequentially; every rank receives the full result."""
    from bluefog_tpu.parallel.pipeline import pipeline_apply
    n_pp, M, mb, d = 4, 6, 3, 8
    rng = np.random.RandomState(0)
    Ws = jnp.asarray(rng.randn(n_pp, d, d) * 0.5, jnp.float32)
    x = jnp.asarray(rng.randn(M, mb, d), jnp.float32)

    ref = x
    for i in range(n_pp):
        ref = _mlp_stage(Ws[i], ref)

    mesh = Mesh(np.asarray(devices[:n_pp]), ("pp",))
    out = jax.jit(jax.shard_map(
        lambda W, x: pipeline_apply(
            lambda w, xb: _mlp_stage(w[0], xb), W, x, axis_name="pp"),
        mesh=mesh, in_specs=(P("pp"), P()), out_specs=P(),
        check_vma=False))(Ws, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_grads_match_sequential(devices):
    """Reverse-mode AD through the scan+ppermute schedule equals sequential
    backprop — training-capable pipelining with no hand-written backward."""
    from bluefog_tpu.parallel.pipeline import pipeline_apply
    n_pp, M, mb, d = 4, 5, 2, 6
    rng = np.random.RandomState(1)
    Ws = jnp.asarray(rng.randn(n_pp, d, d) * 0.5, jnp.float32)
    x = jnp.asarray(rng.randn(M, mb, d), jnp.float32)
    mesh = Mesh(np.asarray(devices[:n_pp]), ("pp",))

    def loss_seq(Ws):
        h = x
        for i in range(n_pp):
            h = _mlp_stage(Ws[i], h)
        return jnp.sum(h ** 2)

    def loss_pp(Ws):
        out = jax.shard_map(
            lambda W, xb: pipeline_apply(
                lambda w, z: _mlp_stage(w[0], z), W, xb, axis_name="pp"),
            mesh=mesh, in_specs=(P("pp"), P()), out_specs=P(),
            check_vma=False)(Ws, x)
        return jnp.sum(out ** 2)

    g_ref = jax.grad(loss_seq)(Ws)
    g_pp = jax.jit(jax.grad(loss_pp))(Ws)
    np.testing.assert_allclose(np.asarray(g_pp), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_pipeline_transformer_blocks(devices):
    """Pipeline the TransformerLM's blocks across 2 stages: equals the
    single-device model applied to the same microbatches."""
    from bluefog_tpu.models.transformer import Block, local_attention
    from bluefog_tpu.models import TransformerConfig
    from bluefog_tpu.parallel.pipeline import pipeline_apply

    cfg = TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                            embed_dim=32, max_seq_len=8, dtype=jnp.float32)
    block = Block(cfg, local_attention)
    rng = np.random.RandomState(2)
    M, mb, S = 4, 2, 8
    x = jnp.asarray(rng.randn(M, mb, S, cfg.embed_dim), jnp.float32)
    p0 = block.init(jax.random.PRNGKey(0), x[0])
    p1 = block.init(jax.random.PRNGKey(1), x[0])

    ref = jax.vmap(lambda xb: block.apply(
        p1, block.apply(p0, xb)))(x)

    stacked = jax.tree.map(lambda a, b: jnp.stack([a, b]), p0, p1)
    mesh = Mesh(np.asarray(devices[:2]), ("pp",))
    out = jax.jit(jax.shard_map(
        lambda W, xb: pipeline_apply(
            lambda w, z: block.apply(jax.tree.map(lambda a: a[0], w), z),
            W, xb, axis_name="pp"),
        mesh=mesh, in_specs=(P("pp"), P()), out_specs=P(),
        check_vma=False))(stacked, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_moe_expert_parallel_matches_dense(devices):
    """Switch-routed MoE over a 4-rank ep axis == the dense single-device
    evaluation of the same routing plan (incl. capacity drops)."""
    from bluefog_tpu.parallel.moe import moe_apply, switch_dispatch
    E, T, d, C = 4, 12, 8, 4
    rng = np.random.RandomState(0)
    Ws = jnp.asarray(rng.randn(E, d, d) * 0.5, jnp.float32)
    x = jnp.asarray(rng.randn(T, d), jnp.float32)
    logits = jnp.asarray(rng.randn(T, E), jnp.float32)

    # dense reference from the same dispatch plan
    combine, dispatch = switch_dispatch(logits, E, C)
    ref = jnp.zeros_like(x)
    for e in range(E):
        ye = jnp.tanh((dispatch[e] @ x) @ Ws[e])
        ref = ref + jnp.moveaxis(combine, 1, 0)[e] @ ye

    mesh = Mesh(np.asarray(devices[:E]), ("ep",))
    out = jax.jit(jax.shard_map(
        lambda W, x, lg: moe_apply(
            lambda w, z: jnp.tanh(z @ w[0]), W, x, lg,
            axis_name="ep", capacity=C),
        mesh=mesh, in_specs=(P("ep"), P(), P()), out_specs=P(),
        check_vma=False))(Ws, x, logits)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_moe_grads_flow_to_router_and_experts(devices):
    """Router and expert parameters both receive nonzero gradients through
    the gated combine (Switch-style differentiability)."""
    from bluefog_tpu.parallel.moe import moe_apply
    E, T, d = 4, 8, 6
    rng = np.random.RandomState(1)
    Ws = jnp.asarray(rng.randn(E, d, d) * 0.5, jnp.float32)
    Wr = jnp.asarray(rng.randn(d, E) * 0.5, jnp.float32)
    x = jnp.asarray(rng.randn(T, d), jnp.float32)
    mesh = Mesh(np.asarray(devices[:E]), ("ep",))

    def loss(Ws, Wr):
        out = jax.shard_map(
            lambda W, x, lg: moe_apply(
                lambda w, z: jnp.tanh(z @ w[0]), W, x, lg, axis_name="ep"),
            mesh=mesh, in_specs=(P("ep"), P(), P()), out_specs=P(),
            check_vma=False)(Ws, x, x @ Wr)
        return jnp.sum(out ** 2)

    g_w, g_r = jax.jit(jax.grad(loss, argnums=(0, 1)))(Ws, Wr)
    assert float(jnp.abs(g_w).max()) > 0
    assert float(jnp.abs(g_r).max()) > 0


@pytest.mark.parametrize("split_backward", [False, True])
def test_1f1b_pipeline_matches_sequential_grads(split_backward):
    """pipeline_train_step (1F1B, manual in-scan VJP; with and without the
    ZB-H1 split backward) must reproduce the loss and per-stage gradients
    of running the stages sequentially."""
    n, M, mb, d = 4, 8, 3, 5
    mesh = Mesh(np.asarray(jax.devices()[:n]), ("pp",))
    rng = np.random.RandomState(0)
    Ws = jnp.asarray(rng.randn(n, d, d) * 0.5, jnp.float32)
    bs = jnp.asarray(rng.randn(n, d) * 0.1, jnp.float32)
    x = jnp.asarray(rng.randn(M, mb, d), jnp.float32)
    tgt = jnp.asarray(rng.randn(M, mb, d), jnp.float32)

    def stage_fn(p, xb):
        W, b = p
        return jnp.tanh(xb @ W[0] + b[0])

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    from bluefog_tpu.parallel import pipeline_train_step
    loss_pp, grads_pp = jax.jit(jax.shard_map(
        lambda p, xb, tb: pipeline_train_step(
            stage_fn, p, xb, tb, loss_fn, axis_name="pp",
            split_backward=split_backward),
        mesh=mesh, in_specs=((P("pp"), P("pp")), P(), P()),
        out_specs=(P(), (P("pp"), P("pp"))), check_vma=False))(
            (Ws, bs), x, tgt)

    def sequential_loss(params):
        Ws, bs = params
        def per_mb(xb, tb):
            h = xb
            for s in range(n):
                h = jnp.tanh(h @ Ws[s] + bs[s])
            return loss_fn(h, tb)
        return jnp.mean(jax.vmap(per_mb)(x, tgt))

    loss_ref, grads_ref = jax.value_and_grad(sequential_loss)((Ws, bs))
    np.testing.assert_allclose(float(loss_pp), float(loss_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads_pp[0]),
                               np.asarray(grads_ref[0]), rtol=1e-4,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(grads_pp[1]),
                               np.asarray(grads_ref[1]), rtol=1e-4,
                               atol=1e-6)


def test_1f1b_memory_below_gpipe_autodiff():
    """The 1F1B step's compiled temp memory must undercut jax.grad through
    the GPipe scan (whose residuals grow with M) at M >> n."""
    n, M, mb, d = 4, 32, 8, 64
    mesh = Mesh(np.asarray(jax.devices()[:n]), ("pp",))
    rng = np.random.RandomState(1)
    Ws = jnp.asarray(rng.randn(n, d, d) * 0.3, jnp.float32)
    bs = jnp.zeros((n, d), jnp.float32)
    x = jnp.asarray(rng.randn(M, mb, d), jnp.float32)
    tgt = jnp.asarray(rng.randn(M, mb, d), jnp.float32)

    def stage_fn(p, xb):
        W, b = p
        return jnp.tanh(xb @ W[0] + b[0])

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    from bluefog_tpu.parallel import pipeline_apply, pipeline_train_step

    onef1b = jax.jit(jax.shard_map(
        lambda p, xb, tb: pipeline_train_step(
            stage_fn, p, xb, tb, loss_fn, axis_name="pp"),
        mesh=mesh, in_specs=((P("pp"), P("pp")), P(), P()),
        out_specs=(P(), (P("pp"), P("pp"))), check_vma=False))

    def gpipe_loss(params, xb, tb):
        y = jax.shard_map(
            lambda p, xb: pipeline_apply(stage_fn, p, xb, axis_name="pp"),
            mesh=mesh, in_specs=((P("pp"), P("pp")), P()), out_specs=P(),
            check_vma=False)(params, xb)
        return jnp.mean((y - tb) ** 2)

    gpipe = jax.jit(jax.value_and_grad(gpipe_loss))

    def temp_bytes(fn, *args):
        mem = fn.lower(*args).compile().memory_analysis()
        if mem is None:
            pytest.skip("backend exposes no memory analysis")
        return mem.temp_size_in_bytes

    t_1f1b = temp_bytes(onef1b, (Ws, bs), x, tgt)
    t_gpipe = temp_bytes(gpipe, (Ws, bs), x, tgt)
    assert t_1f1b < t_gpipe, (t_1f1b, t_gpipe)


def test_1f1b_composes_with_decentralized_dp():
    """dp x pp composition: each dp rank runs its own 1F1B pipeline (pp
    axis) and the stage parameters are then combined across dp — the
    reference's decentralized data parallelism layered OVER pipeline
    parallelism in one jitted program.

    Oracle: with identical data on every dp rank and an allreduce combine,
    the composed run must stay replica-identical across dp and match the
    plain single-pipeline 1F1B run exactly.  With per-rank data and a
    dynamic one-peer combine, replicas must converge toward consensus."""
    from bluefog_tpu.ops import collective as C
    from bluefog_tpu.ops import schedule as S
    from bluefog_tpu import topology as topo
    from bluefog_tpu.parallel import pipeline_train_step

    dp, pp, M, mb, d = 4, 2, 4, 3, 5
    mesh = Mesh(np.asarray(jax.devices()[:dp * pp]).reshape(dp, pp),
                ("dp", "pp"))
    rng = np.random.RandomState(0)
    Ws = jnp.asarray(rng.randn(dp, pp, d, d) * 0.5, jnp.float32)
    bs = jnp.asarray(rng.randn(dp, pp, d) * 0.1, jnp.float32)
    x_same = jnp.asarray(rng.randn(1, M, mb, d).repeat(dp, 0), jnp.float32)
    t_same = jnp.asarray(rng.randn(1, M, mb, d).repeat(dp, 0), jnp.float32)

    def stage_fn(p, xb):
        W, b = p
        return jnp.tanh(xb @ W[0, 0] + b[0, 0])

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    lr = 0.1
    dyn = S.compile_dynamic(topo.one_peer_exp2_phases(dp), dp)

    def make_step(combine):
        def body(p, xb, tb, step):
            loss, g = pipeline_train_step(
                stage_fn, p, xb[0], tb[0], loss_fn, axis_name="pp")
            new = jax.tree.map(lambda a, b_: a - lr * b_, p, g)
            new = jax.tree.map(lambda a: combine(a, step), new)
            return new, loss
        return jax.jit(jax.shard_map(
            body, mesh=mesh,
            in_specs=((P("dp", "pp"), P("dp", "pp")), P("dp"), P("dp"),
                      P()),
            out_specs=((P("dp", "pp"), P("dp", "pp")), P()),
            check_vma=False))

    # -- oracle: identical data + allreduce over dp == plain 1F1B ---------
    ar_step = make_step(lambda a, step: C.allreduce(a, "dp", average=True))
    params = (Ws[:1].repeat(dp, 0), bs[:1].repeat(dp, 0))  # same init
    for step in range(3):
        params, loss = ar_step(params, x_same, t_same,
                               jnp.asarray(step, jnp.int32))
    W_out = np.asarray(params[0])
    np.testing.assert_allclose(W_out, W_out[:1].repeat(dp, 0),
                               rtol=1e-6, atol=1e-7)  # replica-identical

    pp_mesh = Mesh(np.asarray(jax.devices()[:pp]), ("pp",))
    # per-device stage params must be (1, 1, d, d) exactly as in the
    # composed mesh, so stage_fn's W[0, 0] indexing matches.
    ref = (Ws[0][:, None], bs[0][:, None])  # (pp, 1, d, d) / (pp, 1, d)

    def ref_body(p, xb, tb):
        loss, g = pipeline_train_step(
            stage_fn, p, xb, tb, loss_fn, axis_name="pp")
        return jax.tree.map(lambda a, b_: a - lr * b_, p, g), loss
    ref_step = jax.jit(jax.shard_map(
        ref_body, mesh=pp_mesh,
        in_specs=((P("pp"), P("pp")), P(), P()),
        out_specs=((P("pp"), P("pp")), P()), check_vma=False))
    rp = ref
    for _ in range(3):
        rp, _ = ref_step(rp, x_same[0], t_same[0])
    np.testing.assert_allclose(W_out[0], np.asarray(rp[0])[:, 0],
                               rtol=1e-5, atol=1e-6)

    # -- decentralized: per-rank data + one-peer combine -> consensus -----
    dyn_step = make_step(
        lambda a, step: C.dynamic_neighbor_allreduce(a, step, dyn, "dp"))
    x_diff = jnp.asarray(rng.randn(dp, M, mb, d), jnp.float32)
    t_diff = jnp.asarray(rng.randn(dp, M, mb, d), jnp.float32)
    params = (Ws, bs)
    first_spread = None
    for step in range(8):
        params, loss = dyn_step(params, x_diff, t_diff,
                                jnp.asarray(step, jnp.int32))
        W_now = np.asarray(params[0])
        spread = np.abs(W_now - W_now.mean(0, keepdims=True)).max()
        if first_spread is None:
            first_spread = spread
    assert np.isfinite(float(loss))
    assert spread < first_spread, (spread, first_spread)


@pytest.mark.parametrize("split_backward", [False, True])
def test_interleaved_1f1b_matches_sequential_grads(split_backward):
    """Interleaved 1F1B (v virtual stage chunks per rank; plain and ZB-H1
    split-backward): loss and per-chunk gradients must reproduce the
    sequential n*v-stage stack."""
    n, v, M, mb, d = 4, 2, 6, 3, 5
    S = n * v
    mesh = Mesh(np.asarray(jax.devices()[:n]), ("pp",))
    rng = np.random.RandomState(0)
    # Global stage s = c*n + r lives at chunk_params[r][c]: build from a
    # flat (S, d, d) stack so the sequential oracle is unambiguous.
    Wflat = jnp.asarray(rng.randn(S, d, d) * 0.4, jnp.float32)
    bflat = jnp.asarray(rng.randn(S, d) * 0.1, jnp.float32)
    # rank-major (n, v, ...) layout: [r][c] = stage c*n + r
    Ws = jnp.stack([jnp.stack([Wflat[c * n + r] for c in range(v)])
                    for r in range(n)])
    bs = jnp.stack([jnp.stack([bflat[c * n + r] for c in range(v)])
                    for r in range(n)])
    x = jnp.asarray(rng.randn(M, mb, d), jnp.float32)
    tgt = jnp.asarray(rng.randn(M, mb, d), jnp.float32)

    def stage_fn(p, xb):
        W, b = p
        return jnp.tanh(xb @ W + b)

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    from bluefog_tpu.parallel import pipeline_train_step_interleaved

    def body(p, xb, tb):
        # strip the shard axis: per-device leaves are (1, v, ...)
        loss, g = pipeline_train_step_interleaved(
            stage_fn, jax.tree.map(lambda a: a[0], p), xb, tb, loss_fn,
            axis_name="pp", split_backward=split_backward)
        return loss, jax.tree.map(lambda a: a[None], g)

    loss_pp, grads_pp = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=((P("pp"), P("pp")), P(), P()),
        out_specs=(P(), (P("pp"), P("pp"))), check_vma=False))(
            (Ws, bs), x, tgt)

    def sequential_loss(flat):
        Wf, bf = flat
        def per_mb(xb, tb):
            h = xb
            for s in range(S):
                h = jnp.tanh(h @ Wf[s] + bf[s])
            return loss_fn(h, tb)
        return jnp.mean(jax.vmap(per_mb)(x, tgt))

    loss_ref, grads_ref = jax.value_and_grad(sequential_loss)((Wflat, bflat))
    np.testing.assert_allclose(float(loss_pp), float(loss_ref), rtol=1e-5)
    gW = np.asarray(grads_pp[0])   # (n, v, d, d)
    gb = np.asarray(grads_pp[1])
    for r in range(n):
        for c in range(v):
            s = c * n + r
            np.testing.assert_allclose(gW[r, c], np.asarray(grads_ref[0])[s],
                                       rtol=1e-4, atol=1e-6,
                                       err_msg=f"stage {s} W grads")
            np.testing.assert_allclose(gb[r, c], np.asarray(grads_ref[1])[s],
                                       rtol=1e-4, atol=1e-6,
                                       err_msg=f"stage {s} b grads")


def test_interleaved_v1_degenerates_to_plain_1f1b():
    """v=1 chunk per rank must reproduce pipeline_train_step exactly."""
    n, M, mb, d = 4, 5, 2, 4
    mesh = Mesh(np.asarray(jax.devices()[:n]), ("pp",))
    rng = np.random.RandomState(3)
    Ws = jnp.asarray(rng.randn(n, d, d) * 0.4, jnp.float32)
    bs = jnp.asarray(rng.randn(n, d) * 0.1, jnp.float32)
    x = jnp.asarray(rng.randn(M, mb, d), jnp.float32)
    tgt = jnp.asarray(rng.randn(M, mb, d), jnp.float32)

    def stage_fn(p, xb):
        W, b = p
        return jnp.tanh(xb @ W + b)

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    from bluefog_tpu.parallel import (pipeline_train_step,
                                      pipeline_train_step_interleaved)

    def plain(p, xb, tb):
        loss, g = pipeline_train_step(
            stage_fn, jax.tree.map(lambda a: a[0], p), xb, tb, loss_fn,
            axis_name="pp")
        return loss, jax.tree.map(lambda a: a[None], g)

    def inter(p, xb, tb):
        loss, g = pipeline_train_step_interleaved(
            stage_fn, jax.tree.map(lambda a: a[0][None], p), xb, tb,
            loss_fn, axis_name="pp")
        return loss, jax.tree.map(lambda a: a[0][None], g)

    run = lambda body: jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=((P("pp"), P("pp")), P(), P()),
        out_specs=(P(), (P("pp"), P("pp"))), check_vma=False))(
            (Ws, bs), x, tgt)
    l1, g1 = run(plain)
    l2, g2 = run(inter)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_decentralized_combine_over_tp_sharded_params(devices):
    """The decentralized neighbor combine composes with Megatron-sharded
    parameters: rank-major replicas whose weight matrices are column-
    sharded over a tp axis are averaged over the dp axis shard-by-shard —
    each (dp, tp) device exchanges ONLY its own tp slice (no tp
    collectives, no resharding), and the result matches the dense
    per-replica oracle."""
    from jax.sharding import NamedSharding
    from bluefog_tpu.ops import collective as C
    from bluefog_tpu.ops import schedule as S
    from bluefog_tpu import topology as topo

    dp, tp, d = 4, 2, 8
    mesh = Mesh(np.asarray(devices[:dp * tp]).reshape(dp, tp),
                ("dp", "tp"))
    rng = np.random.RandomState(0)
    # rank-major replicas of a column-parallel weight: (dp, d, 4d),
    # sharded P("dp", None, "tp") — the Megatron qkv/up-proj layout.
    W = jnp.asarray(rng.randn(dp, d, 4 * d), jnp.float32)
    W = jax.device_put(W, NamedSharding(mesh, P("dp", None, "tp")))

    G = topo.ExponentialTwoGraph(dp)
    sched = S.compile_static(G, use_topo_weights=False)

    def combine(w):
        return C.neighbor_allreduce(w[0], sched, "dp")[None]

    fn = jax.jit(jax.shard_map(
        combine, mesh=mesh,
        in_specs=P("dp", None, "tp"), out_specs=P("dp", None, "tp"),
        check_vma=False))
    out = fn(W)
    # The exchange must ride dp ONLY: in the (dp, tp) device grid, dp
    # neighbors are tp devices apart, so every collective-permute pair in
    # the compiled HLO must differ by a multiple of tp.  A tp-axis
    # collective (implicit gather/reshard regression) would pair adjacent
    # device ids.
    import re
    hlo = fn.lower(W).compile().as_text()
    pairs = re.findall(r"source_target_pairs=\{([^}]*(?:\},\{[^}]*)*)\}",
                       hlo)
    found = re.findall(r"\{(\d+),(\d+)\}", " ".join(pairs))
    assert found, "expected ppermute pairs in the compiled HLO"
    for a, b in found:
        assert (int(b) - int(a)) % tp == 0, \
            f"collective pairs devices {a}->{b}: not a dp-axis hop"
    w_uni = S.uniform_weights(topo.weight_matrix(G))
    expected = np.einsum("sd,s...->d...", w_uni, np.asarray(W))
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5,
                               atol=1e-6)


def test_moe_composes_with_decentralized_dp(devices):
    """ep x dp in ONE shard_map program: each dp rank trains its own
    replica of a router + an ep-sharded expert bank, the Switch
    load-balance aux loss in the objective, and the decentralized combine
    on the dp axis (VERDICT r3 next-round #5).

    Oracles: (a) one composed train step with identical data and an
    allreduce dp-combine matches the DENSE single-device step (task +
    aux gradients, incl. the 1/E psum scaling for replicated-router
    grads) exactly; (b) with per-rank data and a static neighbor combine,
    replicas move toward consensus and losses stay finite."""
    from bluefog_tpu.ops import collective as C
    from bluefog_tpu.ops import schedule as S
    from bluefog_tpu import topology as topo
    from bluefog_tpu.parallel.moe import (load_balance_loss, moe_apply,
                                          switch_dispatch)
    from jax import lax

    dp, E, T, d, CAP = 2, 4, 16, 6, 8
    AUXW = 0.01
    lr = 0.1
    mesh = Mesh(np.asarray(jax.devices()[:dp * E]).reshape(dp, E),
                ("dp", "ep"))
    rng = np.random.RandomState(0)
    Ws0 = jnp.asarray(rng.randn(E, d, d) * 0.5, jnp.float32)
    Wr0 = jnp.asarray(rng.randn(d, E) * 0.5, jnp.float32)
    x1 = jnp.asarray(rng.randn(T, d), jnp.float32)
    t1 = jnp.asarray(rng.randn(T, d), jnp.float32)

    # -- dense single-device reference step -------------------------------
    def dense_loss(Ws, Wr, x, t):
        lg = x @ Wr
        combine, dispatch = switch_dispatch(lg, E, CAP)
        y = jnp.zeros_like(x)
        for e in range(E):
            ye = jnp.tanh((dispatch[e] @ x) @ Ws[e])
            y = y + jnp.moveaxis(combine, 1, 0)[e] @ ye
        return jnp.mean((y - t) ** 2) + AUXW * load_balance_loss(lg)

    dWs, dWr = jax.grad(dense_loss, argnums=(0, 1))(Ws0, Wr0, x1, t1)
    ref_Ws = np.asarray(Ws0 - lr * dWs)
    ref_Wr = np.asarray(Wr0 - lr * dWr)

    # -- composed ep x dp step --------------------------------------------
    def body(Ws, Wr, x, t, step, combine):
        # shapes inside: Ws (1, 1, d, d) [dp, ep sharded]; Wr (1, d, E);
        # x/t (1, T, d) [dp sharded].
        def loss_fn(Ws, Wr):
            lg = x[0] @ Wr[0]
            y, aux = moe_apply(lambda w, z: jnp.tanh(z @ w[0, 0]),
                               Ws, x[0], lg, axis_name="ep",
                               capacity=CAP, with_aux=True)
            # Per-rank objective = global loss / E (the moe_apply gradient
            # convention: the psum transpose otherwise inflates every
            # grad by E).
            return ((jnp.mean((y - t[0]) ** 2) + AUXW * aux)
                    / lax.axis_size("ep"))
        loss, (gWs, gWr) = jax.value_and_grad(loss_fn,
                                              argnums=(0, 1))(Ws, Wr)
        gWr = lax.psum(gWr, "ep")  # replicated router: sum ep partials
        loss = lax.psum(loss, "ep")  # true global loss for reporting
        Ws = Ws - lr * gWs
        Wr = Wr - lr * gWr
        # Decentralized combine over the dp axis (replica mixing).
        Ws = combine(Ws, step)
        Wr = combine(Wr, step)
        return Ws, Wr, loss[None]  # (1,): this dp rank's loss

    def make_step(combine):
        return jax.jit(jax.shard_map(
            lambda Ws, Wr, x, t, step: body(Ws, Wr, x, t, step, combine),
            mesh=mesh,
            in_specs=(P("dp", "ep"), P("dp"), P("dp"), P("dp"), P()),
            out_specs=(P("dp", "ep"), P("dp"), P("dp")),
            check_vma=False))

    # (a) identical data + allreduce over dp == the dense step
    ar = make_step(lambda a, s: C.allreduce(a, "dp", average=True))
    Ws = Ws0[None].repeat(dp, 0)                       # (dp, E, d, d)
    Wr = Wr0[None].repeat(dp, 0)                       # (dp, d, E)
    xs = x1[None].repeat(dp, 0)
    ts = t1[None].repeat(dp, 0)
    Ws1, Wr1, loss = ar(Ws, Wr, xs, ts, jnp.asarray(0, jnp.int32))
    for r in range(dp):
        np.testing.assert_allclose(np.asarray(Ws1[r]), ref_Ws,
                                   rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(np.asarray(Wr1[r]), ref_Wr,
                                   rtol=2e-5, atol=2e-6)

    # (b) per-rank data + neighbor combine: finite, converging replicas
    sched = S.compile_static(topo.RingGraph(dp), use_topo_weights=False)
    nar = make_step(lambda a, s: C.neighbor_allreduce(a, sched, "dp"))
    xs2 = jnp.asarray(rng.randn(dp, T, d), jnp.float32)
    ts2 = jnp.asarray(rng.randn(dp, T, d), jnp.float32)
    Ws, Wr = Ws0[None].repeat(dp, 0), Wr0[None].repeat(dp, 0)
    Ws = Ws + jnp.asarray(rng.randn(dp, E, d, d) * 0.1, jnp.float32)
    for s in range(5):
        Ws, Wr, loss = nar(Ws, Wr, xs2, ts2, jnp.asarray(s, jnp.int32))
        assert np.isfinite(float(loss.sum())), s
    spread0 = float(np.abs(np.asarray(Ws)[0] - np.asarray(Ws)[1]).max())
    assert spread0 < 0.1 * 2  # replicas pulled together by the combine


def test_switch_dispatch_mask_excludes_padding():
    """Padding tokens (all-zero logits, argmax -> expert 0) must not occupy
    capacity slots, receive routing, or skew the load-balance statistic
    when the validity mask is supplied."""
    from bluefog_tpu.parallel.moe import load_balance_loss, switch_dispatch
    E, C = 2, 2
    logits = jnp.concatenate([jnp.zeros((3, E), jnp.float32),
                              jnp.asarray([[2.0, 0.0]] * 3, jnp.float32)])
    valid = jnp.asarray([0.0, 0.0, 0.0, 1.0, 1.0, 1.0])
    cm, dm = switch_dispatch(logits, E, C, valid)
    _, du = switch_dispatch(logits, E, C)
    # UNMASKED: the pads fill expert 0's queue, real tokens are dropped.
    assert float(du[0, :, 3:].sum()) == 0.0
    # MASKED: pads route nowhere; the first two real tokens get the slots.
    assert float(dm[0, :, :3].sum()) == 0.0
    assert float(dm[0, :, 3:5].sum()) == 2.0
    assert float(cm[:3].sum()) == 0.0
    # The masked aux loss equals the loss over the real tokens alone.
    np.testing.assert_allclose(float(load_balance_loss(logits, valid)),
                               float(load_balance_loss(logits[3:])),
                               rtol=1e-6)


def test_dp_tp_pp_composed_in_one_program(devices):
    """dp x tp x pp in ONE shard_map program (VERDICT r3 next-round #10):
    each dp replica runs a pp-deep pipeline whose stages are tp-sharded
    Megatron MLPs (column-parallel in, row-parallel out, one psum), with
    the decentralized combine on the dp axis after the optimizer step.

    Oracle at (dp, tp, pp) = (2, 2, 2): identical data + the uniform
    2-ring neighbor combine (== the exact average at dp=2) must reproduce
    the DENSE sequential stack's loss and updated parameters exactly (the
    tp replicated-loss convention — divide the microbatch loss by the tp
    axis size — keeps gradients unscaled)."""
    from bluefog_tpu.ops import collective as C
    from bluefog_tpu.parallel import pipeline_train_step
    from jax import lax

    dp, tp, pp, M, mb, d, hid = 2, 2, 2, 4, 3, 6, 8
    lr = 0.1
    mesh = Mesh(np.asarray(jax.devices()[:dp * tp * pp]).reshape(dp, tp, pp),
                ("dp", "tp", "pp"))
    rng = np.random.RandomState(0)
    Wi = jnp.asarray(rng.randn(pp, d, hid) * 0.4, jnp.float32)
    Wo = jnp.asarray(rng.randn(pp, hid, d) * 0.4, jnp.float32)
    x = jnp.asarray(rng.randn(M, mb, d), jnp.float32)
    tgt = jnp.asarray(rng.randn(M, mb, d), jnp.float32)

    # -- dense sequential reference --------------------------------------
    def seq_loss(params):
        Wi, Wo = params
        def per_mb(xb, tb):
            h = xb
            for s in range(pp):
                h = jnp.maximum(h @ Wi[s], 0.0) @ Wo[s]
            return jnp.mean((h - tb) ** 2)
        return jnp.mean(jax.vmap(per_mb)(x, tgt))

    loss_ref, g_ref = jax.value_and_grad(seq_loss)((Wi, Wo))
    ref_Wi = np.asarray(Wi - lr * g_ref[0])
    ref_Wo = np.asarray(Wo - lr * g_ref[1])

    # -- composed program -------------------------------------------------
    def stage_fn(p, xb):
        wi, wo = p  # local: (1, 1, 1, d, hid/tp), (1, 1, 1, hid/tp, d)
        h = jnp.maximum(xb @ wi[0, 0, 0], 0.0)    # column-parallel
        return lax.psum(h @ wo[0, 0, 0], "tp")    # row-parallel + combine

    def mb_loss(y, t):
        # tp replicated-loss convention: every tp rank computes the same
        # loss from the psum'd activation; dividing by the axis size keeps
        # the psum-transposed gradients exact.
        return jnp.mean((y - t) ** 2) / lax.axis_size("tp")

    # The DECENTRALIZED combine on dp: at dp=2 on a uniform-weight ring,
    # neighbor averaging equals the exact average, so the dense oracle
    # covers the real gossip path (schedule + ppermute pairing on a
    # 3-axis mesh), not just C.allreduce.
    from bluefog_tpu.ops import schedule as S
    from bluefog_tpu import topology as topo
    sched = S.compile_static(topo.RingGraph(dp), use_topo_weights=False)

    def body(p, xb, tb):
        loss, g = pipeline_train_step(
            stage_fn, p, xb[0], tb[0], mb_loss, axis_name="pp")
        p = jax.tree.map(lambda a, b: a - lr * b, p, g)
        p = jax.tree.map(
            lambda a: C.neighbor_allreduce(a, sched, "dp"), p)
        return p, (loss * lax.axis_size("tp"))[None]

    step = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=((P("dp", "tp", "pp"), P("dp", "tp", "pp")),
                  P("dp"), P("dp")),
        out_specs=((P("dp", "tp", "pp"), P("dp", "tp", "pp")), P("dp")),
        check_vma=False))

    # Layouts: Wi (dp, tp, pp, d, hid/tp) — tp shards the HIDDEN axis; the
    # shard_map in_spec shards the leading replica axes, so pre-split the
    # hidden axis into the tp position.
    Wi_l = jnp.stack([Wi[:, :, k * (hid // tp):(k + 1) * (hid // tp)]
                      for k in range(tp)])               # (tp, pp, d, h/tp)
    Wo_l = jnp.stack([Wo[:, k * (hid // tp):(k + 1) * (hid // tp), :]
                      for k in range(tp)])               # (tp, pp, h/tp, d)
    Wi_g = Wi_l[None].repeat(dp, 0)                      # (dp, tp, pp, ...)
    Wo_g = Wo_l[None].repeat(dp, 0)
    xs = x[None].repeat(dp, 0)
    ts = tgt[None].repeat(dp, 0)

    (Wi1, Wo1), loss = step((Wi_g, Wo_g), xs, ts)
    np.testing.assert_allclose(float(loss[0]), float(loss_ref), rtol=1e-5)
    # Reassemble the tp shards and compare every dp replica to the dense
    # sequential update.
    for r in range(dp):
        got_Wi = np.concatenate([np.asarray(Wi1[r, k]) for k in range(tp)],
                                axis=-1)
        got_Wo = np.concatenate([np.asarray(Wo1[r, k]) for k in range(tp)],
                                axis=-2)
        np.testing.assert_allclose(got_Wi, ref_Wi, rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(got_Wo, ref_Wo, rtol=2e-5, atol=2e-6)


def test_dp_tp_pp_ep_composed_in_one_program(devices):
    """ALL FOUR parallelism forms in ONE shard_map program (VERDICT r4
    next-round #8): each dp replica runs a pipeline (pp) of stages whose
    dense sublayer is tensor-parallel and whose switch-MoE sublayer is
    expert-parallel — on 8 devices tp and ep share the model-parallel
    'mp' mesh axis (a real deployment pattern; the 16+-device dryrun uses
    distinct axes) — and the decentralized ring combine mixes the dp
    replicas after the update.  Oracle: with identical data, one composed
    step equals the DENSE sequential step exactly (loss and all four
    parameter families: tp-sharded dense in/out, expert-local, replicated
    router), pinning every gradient psum in the composition."""
    from jax import lax

    from bluefog_tpu.ops import collective as C
    from bluefog_tpu.ops import schedule as S
    from bluefog_tpu import topology as topo
    from bluefog_tpu.parallel import moe_apply, pipeline_train_step
    from bluefog_tpu.parallel.moe import switch_dispatch

    dp, mp, pp = 2, 2, 2
    d, hid, E, M, mb, CAP = 6, 8, 2, 4, 4, 4
    lr = 0.1
    mesh = Mesh(np.asarray(devices[:8]).reshape(dp, mp, pp),
                ("dp", "mp", "pp"))
    rng = np.random.RandomState(0)
    Wi = jnp.asarray(rng.randn(pp, d, hid) * 0.4, jnp.float32)
    Wo = jnp.asarray(rng.randn(pp, hid, d) * 0.4, jnp.float32)
    We = jnp.asarray(rng.randn(pp, E, d, d) * 0.4, jnp.float32)
    Wr = jnp.asarray(rng.randn(pp, d, E) * 0.4, jnp.float32)
    x = jnp.asarray(rng.randn(M, mb, d), jnp.float32)
    tgt = jnp.asarray(rng.randn(M, mb, d), jnp.float32)

    # -- dense sequential reference ---------------------------------------
    def dense_loss(Wi, Wo, We, Wr):
        def stage(s, z):
            y = jnp.maximum(z @ Wi[s], 0.0) @ Wo[s]
            lg = y @ Wr[s]
            combine, dispatch = switch_dispatch(lg, E, CAP)
            y2 = jnp.zeros_like(y)
            for e in range(E):
                ye = jnp.tanh((dispatch[e] @ y) @ We[s, e])
                y2 = y2 + jnp.moveaxis(combine, 1, 0)[e] @ ye
            return y + y2
        losses = []
        for m in range(M):
            z = x[m]
            for s in range(pp):
                z = stage(s, z)
            losses.append(jnp.mean((z - tgt[m]) ** 2))
        return jnp.mean(jnp.asarray(losses))

    loss_ref, g_ref = jax.value_and_grad(dense_loss, argnums=(0, 1, 2, 3))(
        Wi, Wo, We, Wr)
    refs = [np.asarray(w - lr * g)
            for w, g in zip((Wi, Wo, We, Wr), g_ref)]

    # -- composed program --------------------------------------------------
    NL = 3  # leading (dp, mp, pp) mesh dims on every param leaf

    def stage_fn(p, xb):
        wi, wo, we, wr = (a.reshape(a.shape[NL:]) for a in p)
        h = jnp.maximum(xb @ wi, 0.0)             # column-parallel
        y = lax.psum(h @ wo, "mp")                # row-parallel + combine
        y2 = moe_apply(lambda w, z: jnp.tanh(z @ w), we, y, y @ wr,
                       axis_name="mp", capacity=CAP)
        return y + y2

    def mb_loss(y, t):
        # Replicated-loss convention: the output is psum-replicated over
        # mp, so divide the per-rank objective by the axis size.
        return jnp.mean((y - t) ** 2) / lax.axis_size("mp")

    sched = S.compile_static(topo.RingGraph(dp), use_topo_weights=False)

    def body(p, xb, tb):
        loss, g = pipeline_train_step(stage_fn, p, xb[0], tb[0], mb_loss,
                                      axis_name="pp")
        gwi, gwo, gwe, gwr = g
        gwr = lax.psum(gwr, "mp")    # replicated router: sum partials
        p = jax.tree.map(lambda a, b: a - lr * b, p, (gwi, gwo, gwe, gwr))
        p = jax.tree.map(lambda a: C.neighbor_allreduce(a, sched, "dp"), p)
        return p, (loss * lax.axis_size("mp"))[None]

    P4 = P("dp", "mp", "pp")
    step = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=((P4, P4, P4, P4), P("dp"), P("dp")),
        out_specs=((P4, P4, P4, P4), P("dp")), check_vma=False))

    hs = hid // mp
    Wi_l = jnp.stack([Wi[:, :, k * hs:(k + 1) * hs] for k in range(mp)])
    Wo_l = jnp.stack([Wo[:, k * hs:(k + 1) * hs, :] for k in range(mp)])
    We_l = jnp.stack([We[:, k] for k in range(mp)])   # expert k on mp rank k
    Wr_l = jnp.stack([Wr for _ in range(mp)])         # replicated router
    lead = lambda a: jnp.broadcast_to(a[None], (dp,) + a.shape)
    params = tuple(lead(a) for a in (Wi_l, Wo_l, We_l, Wr_l))
    xs = jnp.broadcast_to(x[None], (dp,) + x.shape)
    ts = jnp.broadcast_to(tgt[None], (dp,) + tgt.shape)

    newp, loss = step(params, xs, ts)
    np.testing.assert_allclose(float(loss[0]), float(loss_ref), rtol=1e-5)
    for r in range(dp):
        got = (
            np.concatenate([np.asarray(newp[0][r, k]) for k in range(mp)],
                           axis=-1),
            np.concatenate([np.asarray(newp[1][r, k]) for k in range(mp)],
                           axis=-2),
            np.stack([np.asarray(newp[2][r, k]) for k in range(mp)],
                     axis=1),
            np.asarray(newp[3][r, 0]),
        )
        for name, g, w in zip(("Wi", "Wo", "We", "Wr"), got, refs):
            np.testing.assert_allclose(g, w, rtol=2e-4, atol=2e-5,
                                       err_msg=name)
