"""Examples double as e2e smoke tests (reference ``docs/code_structure.rst:14-16``)."""

import runpy
import sys

import pytest

# Examples pay a full model build + training loop each; they are the slow
# e2e tier (run ``pytest -m slow`` or the full suite before shipping).
pytestmark = pytest.mark.slow

EXAMPLES = "examples"


def run_example(path, argv):
    old = sys.argv
    sys.argv = [path] + argv
    try:
        runpy.run_path(path, run_name="__main__")
    finally:
        sys.argv = old


def test_average_consensus_static():
    run_example(f"{EXAMPLES}/average_consensus.py",
                ["--dim", "64", "--max-iters", "200"])


def test_average_consensus_dynamic():
    run_example(f"{EXAMPLES}/average_consensus.py",
                ["--dim", "64", "--max-iters", "20", "--dynamic"])


@pytest.mark.parametrize("method,maxerr", [
    ("diffusion", 0.1),          # plain diffusion has O(lr) bias
    ("exact_diffusion", 1e-3),
    ("gradient_tracking", 1e-3),
    ("push_diging", 1e-3),
])
def test_decentralized_algorithms_reach_minimizer(method, maxerr, capsys):
    run_example(f"{EXAMPLES}/decentralized_optimization.py",
                ["--method", method])
    out = capsys.readouterr().out
    err = float(out.strip().split()[-1])
    assert err < maxerr, f"{method}: {err}"


def test_mnist_lenet_short():
    run_example(f"{EXAMPLES}/mnist_lenet.py",
                ["--epochs", "6", "--per-rank-samples", "256",
                 "--batch-size", "64"])


@pytest.mark.parametrize("optimizer", ["neighbor_allreduce", "push_sum"])
def test_elastic_training_preempt_then_resume(tmp_path, capsys, optimizer):
    """The elastic example self-preempts mid-run, then a second invocation
    resumes from the checkpoint and finishes — bit-identically to an
    uninterrupted run (for push_sum this covers the window store riding
    the checkpoint: staging mass + associated-P)."""
    d = str(tmp_path / "ck")
    base = ["--steps", "20", "--save-every", "5", "--optimizer", optimizer]
    with pytest.raises(SystemExit) as ei:
        run_example(f"{EXAMPLES}/elastic_training.py",
                    ["--ckpt-dir", d] + base + ["--preempt-at-step", "12"])
    assert ei.value.code == 75
    assert "preempted; checkpoint saved at step 12" in capsys.readouterr().out
    run_example(f"{EXAMPLES}/elastic_training.py", ["--ckpt-dir", d] + base)
    resumed = capsys.readouterr().out
    assert "done: 20 steps" in resumed

    # Uninterrupted reference run in a fresh directory: identical final loss.
    run_example(f"{EXAMPLES}/elastic_training.py",
                ["--ckpt-dir", str(tmp_path / "ref")] + base)
    ref = capsys.readouterr().out
    final = [l for l in resumed.splitlines() if l.startswith("done:")][0]
    final_ref = [l for l in ref.splitlines() if l.startswith("done:")][0]
    assert final == final_ref, (final, final_ref)


def test_benchmark_harness_tiny():
    run_example(f"{EXAMPLES}/benchmark.py",
                ["--model", "lenet", "--batch-size", "4",
                 "--num-warmup-batches", "1", "--num-iters", "2",
                 "--num-batches-per-iter", "2"])


def test_tensor_parallel_training_example(capsys):
    """2-way dp x 4-way tp training: loss falls and the qkv kernel really
    carries a tp-sharded layout."""
    run_example(f"{EXAMPLES}/tensor_parallel_training.py",
                ["--steps", "40"])
    out = capsys.readouterr().out
    assert "done: loss" in out
    assert "kernel sharding PartitionSpec(None, 'tp')" in out


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b", "zb"])
def test_pipeline_training_example(capsys, schedule):
    """Pipelined training (GPipe-through-AD and 1F1B): one stage per
    device, loss falls, pipelined forward equals the sequential stack."""
    run_example(f"{EXAMPLES}/pipeline_training.py",
                ["--steps", "60", "--schedule", schedule])
    out = capsys.readouterr().out
    assert "matches the sequential stack" in out
    if schedule in ("1f1b", "zb"):
        assert "compiled temp memory" in out


def test_text_generation_example(capsys):
    """Train-then-generate round trip: greedy decoding reproduces the
    memorized text exactly through the KV cache."""
    run_example(f"{EXAMPLES}/text_generation.py",
                ["--steps", "300", "--max-new-tokens", "32"])
    assert "matches the training text exactly" in capsys.readouterr().out


@pytest.mark.parametrize("attn", ["ring", "ulysses"])
def test_long_context_training_example(attn, capsys):
    """Sequence-parallel LM training: loss falls with the sequence sharded
    over the 8-rank mesh."""
    run_example(f"{EXAMPLES}/long_context_training.py",
                ["--seq-len", "512", "--steps", "12", "--attention", attn,
                 "--rope"])
    out = capsys.readouterr().out
    assert "done: loss" in out
    # the summary describes each mode's actual memory/communication shape
    assert ("no device materialized" in out) == (attn == "ring")


def test_benchmark_host_data_feed():
    """Batches fed from host RAM through the prefetching pipeline."""
    run_example(f"{EXAMPLES}/benchmark.py",
                ["--model", "lenet", "--batch-size", "4",
                 "--num-warmup-batches", "1", "--num-iters", "2",
                 "--num-batches-per-iter", "1", "--host-data"])


def test_benchmark_scaling_efficiency(capsys):
    """--efficiency measures 1-device vs n-device throughput and prints the
    efficiency ratio (reference protocol pytorch_benchmark.py:228-256)."""
    run_example(f"{EXAMPLES}/benchmark.py",
                ["--model", "lenet", "--batch-size", "4",
                 "--num-warmup-batches", "1", "--num-iters", "2",
                 "--num-batches-per-iter", "1", "--efficiency"])
    out = capsys.readouterr().out
    assert "scaling efficiency at 8 devices:" in out, out
    line = [l for l in out.splitlines() if "scaling efficiency" in l][0]
    eff = float(line.split(":")[1].strip().split("%")[0])
    assert 0.0 < eff, out  # sane ratio; CPU-mesh value itself is meaningless


def test_benchmark_measure_single_device_subset():
    """measure(devices=[one]) runs the whole protocol over a device subset
    (world size 1) — the building block of the efficiency harness."""
    import jax
    sys.path.insert(0, EXAMPLES)
    try:
        import benchmark as bm
    finally:
        sys.path.pop(0)
    args = bm.build_parser().parse_args(
        ["--model", "lenet", "--batch-size", "4", "--num-warmup-batches", "1",
         "--num-iters", "2", "--num-batches-per-iter", "1"])
    mean, ci, n = bm.measure(args, devices=jax.devices()[:1], quiet=True)
    assert n == 1 and mean > 0, (mean, ci, n)
    import bluefog_tpu as bf
    bf.shutdown()


@pytest.mark.parametrize("model,lr", [("lenet", "0.005"), ("vit", "0.01")])
def test_resnet_training_example_converges(capsys, model, lr):
    """Full training protocol (reference pytorch_resnet.py): shard data,
    broadcast, warmup+decay schedule, validate — reaches high accuracy on
    the class-pattern task (CNN and vision-transformer variants)."""
    run_example(f"{EXAMPLES}/resnet_training.py",
                ["--model", model, "--image-size",
                 "28" if model == "lenet" else "32",
                 "--samples-per-rank", "256", "--batch-size", "16",
                 "--epochs", "5", "--base-lr", lr])
    out = capsys.readouterr().out
    acc = float(out.strip().splitlines()[-1].split()[-1])
    assert acc > 0.9, out


def test_resnet_training_checkpoint_resume(tmp_path, capsys):
    """Stop after 1 epoch, resume, finish — the resumed run must announce
    the restart epoch and keep improving (momentum + LR-schedule position
    live in the restored optimizer count)."""
    argv = ["--model", "lenet", "--image-size", "28",
            "--samples-per-rank", "128", "--batch-size", "16",
            "--base-lr", "0.005", "--checkpoint-dir", str(tmp_path / "ck")]
    run_example(f"{EXAMPLES}/resnet_training.py", argv + ["--epochs", "1"])
    first = capsys.readouterr().out
    run_example(f"{EXAMPLES}/resnet_training.py", argv + ["--epochs", "3"])
    out = capsys.readouterr().out
    assert "resumed from epoch 0" in out, out
    assert "epoch 0:" not in out  # did not retrain the finished epoch
    acc = float(out.strip().splitlines()[-1].split()[-1])
    acc_first = float(first.strip().splitlines()[-1].split()[-1])
    assert acc >= acc_first, (first, out)


@pytest.mark.parametrize("method,maxerr,iters", [
    ("admm", 1e-6, 300),
    ("extra", 5e-3, 2500),
    ("exact_diffusion", 5e-3, 2500),
    ("gradient_tracking", 5e-3, 2500),
])
def test_resource_allocation_methods(method, maxerr, iters, capsys):
    """Optimal exchange (reference resource_allocation.ipynb): allocations
    reach the KKT solution and the market clears."""
    run_example(f"{EXAMPLES}/resource_allocation.py",
                ["--method", method, "--iters", str(iters)])
    out = capsys.readouterr().out
    err = float(out.strip().split()[-1])
    assert err < maxerr, f"{method}: {err}"


@pytest.mark.parametrize("combine", ["neighbor", "allreduce"])
def test_moe_training_example(capsys, combine):
    """ep x dp MoE training (switch routing + load-balance aux loss +
    decentralized dp combine in one shard_map program): loss falls."""
    run_example(f"{EXAMPLES}/moe_training.py",
                ["--steps", "60", "--combine", combine])
    out = capsys.readouterr().out
    assert "MOE-TRAINING-OK" in out
