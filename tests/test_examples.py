"""Examples double as e2e smoke tests (reference ``docs/code_structure.rst:14-16``)."""

import runpy
import sys

import pytest

EXAMPLES = "examples"


def run_example(path, argv):
    old = sys.argv
    sys.argv = [path] + argv
    try:
        runpy.run_path(path, run_name="__main__")
    finally:
        sys.argv = old


def test_average_consensus_static():
    run_example(f"{EXAMPLES}/average_consensus.py",
                ["--dim", "64", "--max-iters", "200"])


def test_average_consensus_dynamic():
    run_example(f"{EXAMPLES}/average_consensus.py",
                ["--dim", "64", "--max-iters", "20", "--dynamic"])


@pytest.mark.parametrize("method,maxerr", [
    ("diffusion", 0.1),          # plain diffusion has O(lr) bias
    ("exact_diffusion", 1e-3),
    ("gradient_tracking", 1e-3),
    ("push_diging", 1e-3),
])
def test_decentralized_algorithms_reach_minimizer(method, maxerr, capsys):
    run_example(f"{EXAMPLES}/decentralized_optimization.py",
                ["--method", method])
    out = capsys.readouterr().out
    err = float(out.strip().split()[-1])
    assert err < maxerr, f"{method}: {err}"


def test_mnist_lenet_short():
    run_example(f"{EXAMPLES}/mnist_lenet.py",
                ["--epochs", "6", "--per-rank-samples", "256",
                 "--batch-size", "64"])


def test_benchmark_harness_tiny():
    run_example(f"{EXAMPLES}/benchmark.py",
                ["--model", "lenet", "--batch-size", "4",
                 "--num-warmup-batches", "1", "--num-iters", "2",
                 "--num-batches-per-iter", "2"])
