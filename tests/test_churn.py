"""Churn controller: membership consensus, survivor re-planning, chaos
fault injection, launcher toleration, telemetry surfaces.

The consensus protocol is exercised hermetically — controllers wired
through an in-memory router with a fake clock and injectable probe, no
sockets — and the survivor topology re-plan end-to-end on the 8-device
virtual CPU mesh.  The full multi-process kill-a-rank-mid-gossip path runs
as `make chaos-smoke` (and the slow-marked wrapper at the bottom)."""

import json
import time

import numpy as np
import pytest

import bluefog_tpu as bf
from bluefog_tpu import topology as topo
from bluefog_tpu.ops import membership as M
from bluefog_tpu.utils import chaos as CH
from bluefog_tpu.utils import config, telemetry

N = 8


@pytest.fixture(autouse=True)
def _clean_membership():
    yield
    M.install(None)
    telemetry.reset()
    config.reload()


# ---------------------------------------------------------------------------
# Chaos spec parsing
# ---------------------------------------------------------------------------

def test_parse_chaos_specs():
    faults = CH.parse_chaos(
        "kill:rank=3:step=40, delay:rank=1:step=10:steps=5:ms=50,"
        "partition:rank=2:step=20")
    assert faults[0] == CH.Fault("kill", 3, 40)
    assert faults[1] == CH.Fault("delay", 1, 10, steps=5, ms=50.0)
    assert faults[2] == CH.Fault("partition", 2, 20, steps=20)
    assert faults[1].active_at(10) and faults[1].active_at(14)
    assert not faults[1].active_at(15)
    assert CH.killed_ranks(faults) == [3]
    assert CH.parse_chaos(None) == [] and CH.parse_chaos("") == []


@pytest.mark.parametrize("bad", [
    "explode:rank=0:step=1",          # unknown kind
    "kill:rank=0",                    # missing step
    "kill:step=4",                    # missing rank
    "kill:rank=0:step=4:bogus=1",     # unknown field
    "kill:rank=-1:step=4",            # negative rank
])
def test_parse_chaos_rejects_malformed(bad):
    with pytest.raises(ValueError):
        CH.parse_chaos(bad)


def test_chaos_injector_partition_toggles_transport():
    class FakeTransport:
        def __init__(self):
            self.partitions = []

        def set_partition(self, addrs):
            self.partitions.append(set(addrs) if addrs else set())

    t = FakeTransport()
    inj = CH.ChaosInjector(
        my_ranks=[2],
        faults=CH.parse_chaos("partition:rank=2:step=5:steps=3"),
        transport=t, peer_addrs=[("h", 1), ("h", 2)])
    for step in range(12):
        inj.apply(step)
    # Engaged once at step 5, healed once at step 8 — no flapping.
    assert t.partitions == [{("h", 1), ("h", 2)}, set()]


def test_chaos_injector_ignores_other_ranks():
    inj = CH.ChaosInjector(
        my_ranks=[0], faults=CH.parse_chaos("kill:rank=3:step=1"))
    inj.apply(1)  # rank 3's kill must not fire on rank 0


# ---------------------------------------------------------------------------
# Consensus state machine (hermetic: in-memory router, fake clock)
# ---------------------------------------------------------------------------

class _Gang:
    """In-memory membership gang: n controllers, fake clock, losable
    links, scriptable probe, mid-run joiners (elastic scale-up)."""

    def __init__(self, n, suspect_sec=1.0, straggler_steps=0,
                 drop_prob=0.0, rng=None):
        self.n = n
        self.suspect_sec = suspect_sec
        self.clock = 0.0
        self.dead = set()
        self.drop_prob = drop_prob
        self.rng = rng
        self.ctrls = {}
        for p in range(n):
            self.ctrls[p] = M.MembershipController(
                n, p, {r: r for r in range(n)},
                send_fn=self._send_from(p),
                probe_fn=lambda q: q not in self.dead,
                now_fn=lambda: self.clock,
                suspect_sec=suspect_sec,
                straggler_steps=straggler_steps)

    def _send_from(self, p):
        def send(q, payload):
            if self.drop_prob and self.rng is not None \
                    and self.rng.random() < self.drop_prob:
                return  # lossy link: the state-based protocol must heal
            if q not in self.dead and q in self.ctrls:
                self.ctrls[q].on_message(json.loads(payload.decode()))
        return send

    def add_joiner(self, ranks, grantor: int, endpoint=None):
        """A fresh process granted ``ranks`` by ``grantor``, seeded from
        the grantor's CURRENT view — exactly the gang.py grant contract
        (the grant may race an uncommitted shrink; the protocol heals)."""
        p = max(self.ctrls) + 1
        base = self.ctrls[grantor]
        endpoint = endpoint or f"j:{p}"
        self.ctrls[p] = M.MembershipController(
            self.n, p, dict(base.rank_owner),
            send_fn=self._send_from(p),
            probe_fn=lambda q: q not in self.dead,
            now_fn=lambda: self.clock, suspect_sec=self.suspect_sec,
            active=tuple(base.active), epoch=base.epoch, joining=True,
            my_join_ranks=tuple(ranks), my_endpoint=endpoint)
        base.note_join(p, tuple(ranks), endpoint)
        return p

    def run(self, seconds, dt=0.25):
        t = 0.0
        while t < seconds:
            self.clock += dt
            t += dt
            for p, c in self.ctrls.items():
                if p not in self.dead:
                    c.tick()

    def alive(self):
        return [c for p, c in self.ctrls.items()
                if p not in self.dead and not c.evicted]


def test_consensus_commits_identical_view_on_all_survivors():
    g = _Gang(4)
    g.run(2.0)
    assert all(c.epoch == 0 for c in g.alive())  # stable gang: no churn
    g.dead.add(3)
    g.run(5.0)
    for c in g.alive():
        v = c.view()
        assert v.epoch == 1
        assert v.active_ranks == (0, 1, 2)
        ch = c.poll_change()
        assert ch is not None and ch.removed_ranks == (3,)
        assert c.poll_change() is None  # one commit, one change


def test_consensus_survives_two_sequential_failures():
    g = _Gang(5)
    g.dead.add(4)
    g.run(5.0)
    g.dead.add(3)
    g.run(5.0)
    for c in g.alive():
        assert c.epoch == 2
        assert c.view().active_ranks == (0, 1, 2)


def test_reachable_but_silent_peer_needs_hard_timeout():
    """A peer whose listener still answers TCP (probe green) but whose
    heartbeats stopped (partition, wedged process) is evicted only after
    the 3x hard-silence window — never on the soft threshold alone."""
    g = _Gang(3, suspect_sec=1.0)
    g.run(1.0)

    # Proc 2 goes silent but stays probe-reachable: drop its sends without
    # marking it dead.
    g.ctrls[2].send_fn = lambda q, payload: None
    silent_since = g.clock
    while g.clock < silent_since + 2.0:
        g.run(0.25)
    assert all(c.epoch == 0 for p, c in g.ctrls.items() if p != 2)
    while g.clock < silent_since + 5.0:
        g.run(0.25)
    for p in (0, 1):
        assert g.ctrls[p].epoch == 1
        assert g.ctrls[p].view().active_ranks == (0, 1)


def test_straggler_eviction_requires_opt_in():
    g_off = _Gang(3, straggler_steps=0)
    g_on = _Gang(3, straggler_steps=10)
    for g in (g_off, g_on):
        for step in range(40):
            g.clock += 0.25
            for p, c in g.ctrls.items():
                # Rank 2 is alive and heartbeating but stuck at step 3.
                c.note_step(3 if p == 2 else step)
                c.tick()
    assert all(c.epoch == 0 for c in g_off.ctrls.values())
    assert g_on.ctrls[0].epoch == 1
    assert g_on.ctrls[0].view().active_ranks == (0, 1)
    # The straggler itself learns it was voted out.
    assert g_on.ctrls[2].evicted
    ev = g_on.ctrls[2].poll_change()
    assert ev is not None and ev.evicted


def test_withdrawn_proposal_cannot_back_a_commit():
    """A peer's prop=None heartbeat WITHDRAWS its proposal: a commit must
    never be evaluated against votes already retracted (a transiently
    suspected rank that refuted the suspicion would otherwise be evicted
    on stale agreements)."""
    clock = [0.0]
    ctrl = M.MembershipController(
        4, 0, {r: r for r in range(4)}, send_fn=lambda q, p: None,
        probe_fn=lambda q: q != 3, now_fn=lambda: clock[0],
        suspect_sec=1.0)

    def hb(proc, prop):
        ctrl.on_message({"k": "hb", "proc": proc, "epoch": 0, "step": 0,
                         "active": [0, 1, 2, 3], "prop": prop})

    hb(1, [0, 1, 2])
    hb(2, [0, 1, 2])
    hb(1, None)   # both withdraw: proc 3 refuted their suspicion
    hb(2, None)
    clock[0] += 2.0   # now proc 3 goes stale for US too
    hb(1, None)
    hb(2, None)
    ctrl.tick()       # we propose {0,1,2} — but 1 and 2 no longer do
    assert ctrl.epoch == 0
    hb(1, [0, 1, 2])  # fresh agreement: NOW the commit is legitimate
    hb(2, [0, 1, 2])
    ctrl.tick()
    assert ctrl.epoch == 1
    assert ctrl.view().active_ranks == (0, 1, 2)


def test_same_epoch_divergent_views_reconcile_by_intersection():
    """Two processes that raced their commits from different proposal
    snapshots can land on the same epoch with different survivor sets;
    the views must reconcile (monotone intersection), not coexist."""
    def mk(my):
        c = M.MembershipController(
            4, my, {r: r for r in range(4)}, send_fn=lambda q, p: None,
            probe_fn=lambda q: True, now_fn=lambda: 0.0)
        c.epoch = 1
        c.active = frozenset({0, 1, 2})
        return c

    c0 = mk(0)
    c0.on_message({"k": "hb", "proc": 1, "epoch": 1, "step": 0,
                   "active": [0, 1], "prop": None})
    assert c0.epoch == 1
    assert c0.view().active_ranks == (0, 1)
    ch = c0.poll_change()
    assert ch is not None and ch.removed_ranks == (2,)
    # The rank outside the intersection receives the verdict.
    c2 = mk(2)
    c2.on_message({"k": "hb", "proc": 1, "epoch": 1, "step": 0,
                   "active": [0, 1], "prop": None})
    assert c2.evicted


def test_summary_does_no_probing_and_reports_hard_silence_only():
    """/healthz must never block on a dead host's connect timeout: the
    summary path takes no probe verdicts, so suspicion shows up there on
    the hard-silence window only."""
    probes = []
    clock = [0.0]
    ctrl = M.MembershipController(
        3, 0, {r: r for r in range(3)}, send_fn=lambda q, p: None,
        probe_fn=lambda q: probes.append(q) or False,
        now_fn=lambda: clock[0], suspect_sec=1.0)
    clock[0] = 2.0  # peers soft-stale
    assert ctrl.summary()["suspect_ranks"] == []
    assert probes == []  # summary never probed
    clock[0] = 4.0  # past the 3x hard-silence window
    assert ctrl.summary()["suspect_ranks"] == [1, 2]
    assert probes == []


def test_epoch_ahead_heartbeat_adopts_or_evicts():
    g = _Gang(4)
    # A peer that committed ahead and still includes us: adopt.
    g.ctrls[1].on_message({"k": "hb", "proc": 0, "epoch": 3, "step": 0,
                           "active": [0, 1], "prop": None})
    assert g.ctrls[1].epoch == 3
    assert g.ctrls[1].view().active_ranks == (0, 1)
    assert not g.ctrls[1].evicted
    # A committed view that excludes us: eviction verdict.
    g.ctrls[2].on_message({"k": "hb", "proc": 0, "epoch": 2, "step": 0,
                           "active": [0, 1], "prop": None})
    assert g.ctrls[2].evicted


def test_commit_publishes_telemetry_and_health_block():
    telemetry.reset()
    g = _Gang(4)
    M.install(g.ctrls[0])
    assert telemetry.health().get("membership", {}).get("epoch") == 0
    g.dead.add(2)
    g.run(5.0)
    snap = telemetry.snapshot()
    assert snap.get("bf_membership_changes_total") == 1.0
    assert snap.get("bf_active_ranks") == 3.0
    assert snap.get("bf_membership_epoch") == 1.0
    assert snap.get("bf_churn_last_change_timestamp", 0) > 0
    hz = telemetry.health()
    m = hz["membership"]
    assert m["epoch"] == 1 and m["active_ranks"] == [0, 1, 3]
    assert m["changes_total"] == 1 and not m["evicted"]


def test_health_has_no_membership_block_when_churn_off():
    assert "membership" not in telemetry.health()


def test_handle_wire_drops_garbage_and_without_controller():
    M.handle_wire(b"not json")        # no controller: dropped
    g = _Gang(2)
    M.install(g.ctrls[0])
    M.handle_wire(b"\xff\xfe not json")  # undecodable: logged, dropped
    M.handle_wire(json.dumps(
        {"k": "hb", "proc": 1, "epoch": 0, "step": 7,
         "active": [0, 1], "prop": None}).encode())
    assert g.ctrls[0].peer_step[1] == 7


# ---------------------------------------------------------------------------
# Elastic scale-up: join proposals through the same consensus
# ---------------------------------------------------------------------------

def test_join_commits_single_grow_epoch_on_all_members():
    g = _Gang(4)
    g.dead.add(2)
    g.run(5.0)
    j = g.add_joiner([2], grantor=0)
    g.run(3.0)
    for c in g.alive():
        assert c.epoch == 2
        assert c.view().active_ranks == (0, 1, 2, 3)
        assert c.rank_owner[2] == j
    joiner = g.ctrls[j]
    assert not joiner.joining
    # Members saw two commits (shrink + grow); the grow view names the
    # admitted proc, its ranks and its endpoint.
    views = []
    while True:
        v = g.ctrls[0].poll_change()
        if v is None:
            break
        views.append(v)
    assert [v.epoch for v in views] == [1, 2]
    assert views[1].added_procs == (j,)
    assert views[1].added_ranks == (2,)
    assert views[1].added_endpoints == {j: f"j:{j}"}


def test_join_heartbeats_are_byte_identical_without_joins():
    """BLUEFOG_TPU_ELASTIC_JOIN=0 oracle: with no join anywhere in
    flight, the membership wire payload is byte-for-byte the PR-14
    format — the new keys only appear when a join is live."""
    c = M.MembershipController(3, 1, {r: r for r in range(3)},
                               send_fn=lambda q, p: None)
    c.my_step = 7
    legacy = json.dumps({"k": "hb", "proc": 1, "epoch": 0, "step": 7,
                         "active": [0, 1, 2], "prop": None}).encode()
    assert c._payload(None) == legacy
    legacy_prop = json.dumps({"k": "hb", "proc": 1, "epoch": 0, "step": 7,
                              "active": [0, 1, 2],
                              "prop": [0, 1]}).encode()
    assert c._payload(frozenset({0, 1})) == legacy_prop


def test_same_epoch_superset_views_reconcile_by_joiner_union():
    """The intersection-reconcile rule extended to supersets: a proc
    admitted AT the contested epoch rides the union (its committer
    verified full agreement), while incumbents still intersect."""
    def mk(my):
        c = M.MembershipController(
            4, my, {r: r for r in range(4)}, send_fn=lambda q, p: None,
            probe_fn=lambda q: True, now_fn=lambda: 0.0)
        return c

    # A committed {0,1,3} at epoch 2 without the joiner; B committed
    # {0,1,3,4} at epoch 2 WITH joiner 4 (joined at this epoch, owning
    # rank 2).  A must fold the joiner in, not drop it.
    a = mk(0)
    a.epoch, a.active = 2, frozenset({0, 1, 3})
    a.on_message({"k": "hb", "proc": 1, "epoch": 2, "step": 0,
                  "active": [0, 1, 3, 4], "prop": None,
                  "joined": [4], "joined_ranks": {"4": [2]},
                  "joined_eps": {"4": "j:4"}})
    assert a.epoch == 2
    assert a.active == frozenset({0, 1, 3, 4})
    assert a.rank_owner[2] == 4
    v = a.poll_change()
    assert v is not None and v.added_procs == (4,)
    # And the mirror: B hears A's joiner-less epoch-2 view — the joiner
    # stays (B's own joined_at_epoch rides the union term).
    b = mk(1)
    b.epoch, b.active = 2, frozenset({0, 1, 3, 4})
    b.joined_at_epoch = frozenset({4})
    b.joined_info[4] = ((2,), "j:4")
    b.rank_owner[2] = 4
    b.on_message({"k": "hb", "proc": 0, "epoch": 2, "step": 0,
                  "active": [0, 1, 3], "prop": None})
    assert b.active == frozenset({0, 1, 3, 4})


def test_epoch_ahead_heartbeat_adopts_grown_view_with_rank_claims():
    """A peer that slept through the whole join adopts the grown view —
    including the joiner's rank takeover — from one heartbeat."""
    c = M.MembershipController(4, 3, {r: r for r in range(4)},
                               send_fn=lambda q, p: None)
    c.on_message({"k": "hb", "proc": 0, "epoch": 2, "step": 0,
                  "active": [0, 1, 3, 4], "prop": None,
                  "joined": [4], "joined_ranks": {"4": [2]},
                  "joined_eps": {"4": "10.0.0.9:7001"}})
    assert c.epoch == 2
    assert c.rank_owner[2] == 4
    assert c.view().active_ranks == (0, 1, 2, 3)
    assert c.peer_endpoint_hint(4) == ("10.0.0.9", 7001)


def test_joining_process_rebases_instead_of_self_evicting():
    """A second shrink committing while the join is in flight must not
    read as an eviction verdict for the joiner — it was never a member.
    The joiner rebases on the newer survivor set and is admitted into
    the NEXT epoch."""
    g = _Gang(4)
    g.dead.add(3)
    g.run(5.0)  # epoch 1: {0,1,2}
    # Grant from a STALE base: proc 0's view BEFORE another kill.
    j = g.add_joiner([3], grantor=0)
    g.dead.add(2)
    g.run(5.0)
    joiner = g.ctrls[j]
    assert not joiner.evicted
    assert not joiner.joining
    for c in g.alive():
        assert c.active == frozenset({0, 1, j})
        assert c.rank_owner[3] == j


def test_property_interleaved_joins_and_kills_never_diverge():
    """Satellite property test: random interleavings of kill + join
    (including grants raced against uncommitted shrinks and lossy
    links) always converge every survivor AND the joiner to ONE
    identical (epoch, active, rank ownership) view — never divergent
    committed views, never a lost joiner."""
    import random
    for seed in range(10):
        rng = random.Random(seed)
        g = _Gang(4, drop_prob=0.15, rng=rng)
        g.run(1.0)
        victim = rng.choice([1, 2, 3])
        g.dead.add(victim)
        # The join lands at a random point relative to the shrink
        # consensus: sometimes before the commit, sometimes after.
        g.run(rng.uniform(0.25, 6.0))
        grantor = rng.choice(sorted(set(g.ctrls) - g.dead))
        j = g.add_joiner([victim], grantor=grantor)
        g.run(14.0)
        alive = g.alive()
        assert g.ctrls[j] in alive, f"seed {seed}: joiner lost"
        views = {(c.epoch, c.active) for c in alive}
        assert len(views) == 1, f"seed {seed}: divergent views {views}"
        for c in alive:
            assert c.rank_owner[victim] == j, f"seed {seed}"
            assert c.view().active_ranks == tuple(range(4)), \
                f"seed {seed}: {c.view()}"


# ---------------------------------------------------------------------------
# Survivor topology + live re-plan through set_topology
# ---------------------------------------------------------------------------

def test_survivor_topology_is_doubly_stochastic_with_isolated_dead():
    t = M.survivor_topology(8, [0, 2, 3, 5, 6])
    w = topo.weight_matrix(t)
    assert w.shape == (8, 8)
    np.testing.assert_allclose(w.sum(axis=0), 1.0)
    np.testing.assert_allclose(w.sum(axis=1), 1.0)
    for dead in (1, 4, 7):
        assert w[dead, dead] == 1.0
        assert np.count_nonzero(w[dead]) == 1
        assert np.count_nonzero(w[:, dead]) == 1
    # Survivors form one connected gossip component.
    import networkx as nx
    sub = t.subgraph([0, 2, 3, 5, 6])
    assert nx.is_strongly_connected(sub)


def test_survivor_topology_validates_input():
    with pytest.raises(ValueError):
        M.survivor_topology(4, [])
    with pytest.raises(ValueError):
        M.survivor_topology(4, [0, 0, 1])
    with pytest.raises(ValueError):
        M.survivor_topology(4, [0, 9])


def test_set_topology_replan_over_survivors():
    """The recovery re-plan end to end on the virtual mesh: installing the
    survivor topology re-enters the ordinary set_topology pipeline and
    gossip averages over survivors only — dead ranks' rows ride their
    identity self-loop, untouched."""
    bf.init()
    try:
        survivors = [0, 1, 2, 4, 6, 7]
        t = M.survivor_topology(N, survivors)
        bf.set_topology(t, is_weighted=True)
        x = np.stack([np.full(3, i, np.float32) for i in range(N)])
        out = np.asarray(bf.neighbor_allreduce(x))
        w = topo.weight_matrix(t)
        expected = np.einsum("sd,s...->d...", w, x)
        np.testing.assert_allclose(out, expected, rtol=1e-5)
        for dead in (3, 5):
            np.testing.assert_allclose(out[dead], x[dead], rtol=1e-6)
    finally:
        bf.shutdown()


# ---------------------------------------------------------------------------
# Launcher: --chaos toleration + kill-gang exit summary
# ---------------------------------------------------------------------------

def test_bfrun_parser_accepts_chaos_spec():
    from bluefog_tpu.run.run import build_parser
    args = build_parser().parse_args(
        ["-np", "4", "--chaos", "kill:rank=3:step=40", "python", "x.py"])
    assert args.chaos == "kill:rank=3:step=40"


def test_bfrun_rejects_bad_chaos_spec_and_out_of_range_rank(capsys):
    from bluefog_tpu.run import run as R
    assert R.main(["-np", "2", "--chaos", "explode:rank=0:step=1",
                   "python", "x.py"]) == 2
    assert "unknown fault kind" in capsys.readouterr().err
    assert R.main(["-np", "2", "--chaos", "kill:rank=5:step=1",
                   "python", "x.py"]) == 2
    assert "outside the 2-process gang" in capsys.readouterr().err


class _FakeProc:
    def __init__(self, rc=None):
        self.rc = rc
        self.terminated = self.killed = False

    def poll(self):
        return self.rc

    def wait(self, timeout=None):
        if self.rc is None:
            import subprocess
            raise subprocess.TimeoutExpired("fake", timeout)
        return self.rc

    def terminate(self):
        self.terminated = True

    def kill(self):
        self.killed = True


def test_wait_gang_tolerates_chaos_killed_rank():
    from bluefog_tpu.run import run as R
    # Rank 1 dies by SIGKILL (rc -9, tolerated); ranks 0/2 finish clean.
    procs = [_FakeProc(0), _FakeProc(-9), _FakeProc(0)]
    entries = [(p, "127.0.0.1", False) for p in procs]
    assert R._wait_gang(entries, ["ssh"], "tag", tolerate={1}) == 0
    assert not any(p.terminated or p.killed for p in procs)


def test_wait_gang_still_kills_on_untolerated_failure(capsys):
    from bluefog_tpu.run import run as R
    procs = [_FakeProc(0), _FakeProc(3), _FakeProc(0)]
    entries = [(p, "127.0.0.1", False) for p in procs]
    assert R._wait_gang(entries, ["ssh"], "tag", tolerate={0}) == 3
    err = capsys.readouterr().err
    assert "gang exit summary" in err
    assert "rank 1: exit 3" in err


def test_exit_reason_spellings():
    from bluefog_tpu.run.run import _exit_reason
    assert _exit_reason(0) == "exit 0"
    assert _exit_reason(2) == "exit 2"
    assert _exit_reason(-9) == "killed by SIGKILL"
    assert "UNRESPONSIVE" in _exit_reason(None)


def test_kill_gang_prints_summary_with_escalation(capsys):
    from bluefog_tpu.run import run as R

    class _Hung(_FakeProc):
        def kill(self):
            self.killed = True
            self.rc = -9  # SIGKILL finally lands

        def wait(self, timeout=None):
            if self.killed:
                return self.rc
            import subprocess
            raise subprocess.TimeoutExpired("fake", timeout)

    procs = [_FakeProc(0), _Hung()]
    entries = [(p, "127.0.0.1", False) for p in procs]
    R._kill_gang(entries, ["ssh"], "tag", kill_grace=0.2)
    err = capsys.readouterr().err
    assert "rank 0: exit 0" in err
    assert "rank 1: killed by SIGKILL after SIGTERM timeout" in err


# ---------------------------------------------------------------------------
# Config knobs
# ---------------------------------------------------------------------------

def test_churn_config_defaults(monkeypatch):
    cfg = config.reload()
    assert cfg.churn is False
    assert cfg.win_retries == 1
    assert cfg.win_retry_backoff_ms == 50.0
    assert cfg.chaos is None
    monkeypatch.setenv("BLUEFOG_TPU_CHURN", "1")
    monkeypatch.setenv("BLUEFOG_TPU_CHAOS", "kill:rank=1:step=2")
    monkeypatch.setenv("BLUEFOG_TPU_WIN_RETRIES", "4")
    cfg = config.reload()
    assert cfg.churn and cfg.win_retries == 4
    assert cfg.chaos == "kill:rank=1:step=2"


def test_supervisor_refuses_without_churn_or_gang(monkeypatch):
    from bluefog_tpu.run.supervisor import ChurnSupervisor, maybe_supervisor
    config.reload()
    with pytest.raises(RuntimeError, match="BLUEFOG_TPU_CHURN"):
        ChurnSupervisor()
    assert maybe_supervisor() is None  # churn off: structurally inert
    monkeypatch.setenv("BLUEFOG_TPU_CHURN", "1")
    config.reload()
    with pytest.raises(RuntimeError, match="multi-process"):
        ChurnSupervisor()  # churn on, but no gang transport
    assert maybe_supervisor() is None  # no transport: still None


# ---------------------------------------------------------------------------
# Full gang (slow tier; `make chaos-smoke` runs the same harness in CI)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_chaos_smoke_end_to_end():
    import subprocess
    import sys
    r = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.tools", "chaos", "--smoke"],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "chaos OK" in r.stdout
