"""Public-surface parity: every name the reference exports from
``bluefog.torch`` (reference ``bluefog/torch/__init__.py:39-77``) must exist
on ``bluefog_tpu`` — a user switching frameworks finds everything they had."""

import numpy as np

import bluefog_tpu as bf

REFERENCE_TORCH_EXPORTS = [
    "allgather", "allgather_nonblocking", "allreduce", "allreduce_",
    "allreduce_nonblocking", "allreduce_nonblocking_", "allreduce_parameters",
    "barrier", "broadcast", "broadcast_", "broadcast_nonblocking",
    "broadcast_nonblocking_", "broadcast_optimizer_state",
    "broadcast_parameters", "get_current_created_window_names",
    "get_skip_negotiate_stage", "get_win_version",
    "hierarchical_neighbor_allreduce",
    "hierarchical_neighbor_allreduce_nonblocking",
    "in_neighbor_machine_ranks", "in_neighbor_ranks", "init",
    "is_homogeneous", "load_machine_topology", "load_topology", "local_rank",
    "local_size", "machine_rank", "machine_size", "mpi_threads_supported",
    "nccl_built", "neighbor_allgather", "neighbor_allgather_nonblocking",
    "neighbor_allreduce", "neighbor_allreduce_nonblocking",
    "out_neighbor_machine_ranks", "out_neighbor_ranks", "poll", "rank",
    "resume", "set_machine_topology", "set_skip_negotiate_stage",
    "set_topology", "shutdown", "size", "suspend", "synchronize",
    "timeline_context", "timeline_end_activity", "timeline_start_activity",
    "turn_off_win_ops_with_associated_p", "turn_on_win_ops_with_associated_p",
    "unified_mpi_window_model_supported", "wait", "win_accumulate",
    "win_accumulate_nonblocking", "win_associated_p", "win_create",
    "win_free", "win_get", "win_get_nonblocking", "win_mutex", "win_poll",
    "win_put", "win_put_nonblocking", "win_update",
    "win_update_then_collect", "win_wait",
]


def test_reference_torch_surface_is_covered():
    missing = [n for n in REFERENCE_TORCH_EXPORTS if not hasattr(bf, n)]
    assert not missing, f"reference API names absent: {missing}"


def test_inplace_aliases_are_functional():
    """The in-place `_` variants return the op result (jax arrays are
    immutable; rebind instead of mutating)."""
    bf.init()
    x = np.ones((bf.size(), 3), np.float32)
    np.testing.assert_allclose(np.asarray(bf.allreduce_(x, average=True)),
                               np.asarray(bf.allreduce(x, average=True)))
    np.testing.assert_allclose(np.asarray(bf.broadcast_(x, 0)),
                               np.asarray(bf.broadcast(x, 0)))
    h = bf.allreduce_nonblocking_(x)
    np.testing.assert_allclose(np.asarray(bf.synchronize(h)),
                               np.asarray(bf.allreduce(x)))


def test_negotiate_and_capability_shims():
    assert bf.get_skip_negotiate_stage() is True
    bf.set_skip_negotiate_stage(False)  # no-op by design
    assert bf.get_skip_negotiate_stage() is True
    assert bf.mpi_threads_supported() is True
    assert bf.nccl_built() is False
    assert bf.unified_mpi_window_model_supported() is True


def test_machine_neighbor_queries():
    bf.init(local_size=4)
    assert bf.machine_size() == 2
    ins = bf.in_neighbor_machine_ranks()
    outs = bf.out_neighbor_machine_ranks()
    assert all(0 <= r < bf.machine_size() for r in ins + outs)
    assert ins and outs  # 2-machine exp graph: each sees the other


def test_broadcast_optimizer_state_pytree():
    import jax
    import jax.numpy as jnp
    import optax
    bf.init()
    n = bf.size()
    params = {"w": jnp.ones((n, 4))}
    state = optax.sgd(0.1, momentum=0.9).init(params)
    # Diverge the momentum buffers per rank, then broadcast rank 2's.
    diverged = jax.tree_util.tree_map(
        lambda b: b + jnp.arange(n, dtype=b.dtype)[:, None]
        if hasattr(b, "ndim") and b.ndim == 2 else b, state)
    out = bf.broadcast_optimizer_state(diverged, root_rank=2)
    assert jax.tree_util.tree_structure(out) == \
        jax.tree_util.tree_structure(state)
    momenta = [np.asarray(b) for b in jax.tree_util.tree_leaves(out)
               if hasattr(b, "ndim") and b.ndim == 2]
    assert momenta, "expected a broadcast momentum buffer"
    for buf in momenta:
        # momentum starts at zeros; rank r's row became r; root 2 broadcast
        np.testing.assert_allclose(buf, np.full((n, 4), 2.0))


def test_beyond_reference_surface_pinned():
    """APIs this framework adds BEYOND the reference's export list — pinned
    so refactors cannot silently drop capability the docs advertise."""
    for name in [
        # ragged gathers (reference allgatherv role)
        "allgather_v", "neighbor_allgather_v",
        # identity
        "owned_ranks",
        # window-state checkpointing
        "win_state_dict", "win_load_state_dict",
        # distributed bootstrap + mesh access
        "init_distributed", "mesh", "hierarchical_mesh",
    ]:
        assert hasattr(bf, name), f"bf.{name} missing"
    from bluefog_tpu import parallel, models
    for name in ["pipeline_apply", "pipeline_train_step",
                 "pipeline_train_step_interleaved", "ring_attention",
                 "ulysses_attention", "tp_param_specs", "moe_apply",
                 "load_balance_loss", "switch_dispatch"]:
        assert hasattr(parallel, name), f"parallel.{name} missing"
    for name in ["ViT", "TransformerLM", "ResNet50", "VGG16", "LeNet5"]:
        assert hasattr(models, name), f"models.{name} missing"
    # round-4 surface: ZB-H1 schedule, push-sum evaluation collect, sharded
    # checkpoints, world-size elastic, rsh launcher hook
    import inspect as _inspect
    assert "split_backward" in _inspect.signature(
        parallel.pipeline_train_step).parameters
    from bluefog_tpu.optim.window_optimizers import DistributedPushSumOptimizer
    assert hasattr(DistributedPushSumOptimizer, "collect")
    from bluefog_tpu.utils import checkpoint as _ck
    for name in ["restore_host", "leaf_shapes", "has_global_shards"]:
        assert hasattr(_ck, name), f"checkpoint.{name} missing"
    from bluefog_tpu.run.run import build_parser
    assert any(a.dest == "rsh" for a in build_parser()._actions), \
        "bfrun lost --rsh"
    # optimizer knobs the docs advertise
    import inspect
    from bluefog_tpu.optim.optimizers import DistributedOptimizer
    sig = inspect.signature(DistributedOptimizer.__init__)
    for kw in ("compression", "fusion", "donate"):
        assert kw in sig.parameters, f"DistributedOptimizer lost {kw}="
    from bluefog_tpu.optim.window_optimizers import DistributedWinPutOptimizer
    sig = inspect.signature(DistributedWinPutOptimizer.__init__)
    for kw in ("fuse", "overlap"):
        assert kw in sig.parameters, f"DistributedWinPutOptimizer lost {kw}="
