"""Communication microbenchmark: the gossip hot path's compiled schedule.

Measures what ``bench.py`` (an end-to-end training benchmark) cannot
isolate: the round count, edge count and per-op walltime of
``neighbor_allreduce`` under the naive shift-distance schedule vs the
min-round repack (``ops/schedule_opt.py``), across the topology families
that matter — shift-structured (ring, Exp2: already optimal, the repack
must be a no-op), star (irregular hub) and random-regular (the stress
case: ~n naive rounds vs degree optimal).

``--transport`` / ``--transport-smoke`` instead run the DCN window
transport loopback microbench (no jax needed): ``WindowTransport``
endpoints on localhost exchange gossip rows across a small-row size
sweep (64 B / 256 B / 4 KB) in three modes — ``legacy`` (one blocking
native RPC + one Python apply per row), ``python`` (the PR-4 coalesced
path: Python sender workers, OP_BATCH frames, vectorized zero-copy
drain) and ``native`` (the C++ hot path: per-peer queues, frame encode,
drain decode + same-slot fold all in ``winsvc.cc``) — plus a
concurrent-peers axis (N client transports round-robin into one server)
reporting msgs/s, MB/s and the drain-burst p50/p99 per configuration.
The smoke variant is the CI gate (``make transport-smoke``): tiny
counts, asserts batched delivery happened, the native path actually
engaged when available, and the batch + native telemetry series exist —
no timing assertion (shared CI boxes jitter); the full variant asserts
the >= 5x native messages/s win over the Python coalesced path for
<= 256 B rows (10x target), and additionally runs the ``ffi`` leg
(below) when the capability is present.

``--ffi`` / ``--ffi-smoke`` run the zero-copy XLA put-path microbench
(``make ffi-smoke``): window puts of DEVICE arrays through a loopback
store in three modes — ``legacy`` (Python coalesced sender), ``native``
(the PR-9 host-staged put feeding the C++ sender) and ``ffi``
(``BLUEFOG_TPU_WIN_XLA``: the XLA buffer pointer handed straight to the
native put-plan executor) — reporting put-side dispatch us/row (flush
factored out of the clock) and end-to-end msgs/s.  The smoke asserts
the FFI path engaged and ``bf_win_host_copy_bytes_total`` reports ZERO
put-side staging bytes for dense f32 rows; the full variant also
asserts the >= 2x dispatch win over the native path for rows >= 4 KiB.

``--hier`` / ``--hier-smoke`` run the hierarchical-gossip report
(``make hier-smoke``): flat static Exp2 vs the two-level mode (dense ICI
inner, sparse one-peer DCN outer with cadence + compression) on
simulated 2x(4x8) and 4x(4x4) multi-slice tori — per-step DCN wire
rows, modeled inter-slice serial link time and simulated consensus
distance, asserting >= 4x DCN reduction at equal-or-better consensus,
plus the end-to-end product-topology equivalence and the sparse codec
OP_BATCH round-trip.

CPU-runnable by design: ppermute schedules compile and execute on the
virtual host-platform mesh, so schedule regressions are caught by
``make bench-comm-smoke`` with no accelerator attached.  On CPU the script
forces ``--n`` virtual devices itself (before jax imports); on a real
backend it uses the attached devices and clamps ``--n`` to them.

Prints ONE JSON line like bench.py:
  {"metric": "gossip_schedule_opt_round_reduction_random_regular",
   "value": <naive_rounds / optimized_rounds>, "unit": "x", ...}
with per-topology detail: rounds/edges before/after, per-op walltime for
both schedules, and the max |naive - optimized| output difference
(must be <= 1e-6 at fp32 — the repack is output-equivalent).
"""

import argparse
import json
import os
import time
from typing import Optional


def _parse_args():
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--n", type=int, default=None,
                   help="mesh/topology size (default: 32 on CPU, else the "
                        "attached device count)")
    p.add_argument("--degree", type=int, default=4,
                   help="random-regular degree (default 4)")
    p.add_argument("--payload", type=int, default=2048,
                   help="per-rank f32 payload elements (default 2048)")
    p.add_argument("--iters", type=int, default=10,
                   help="timed iterations per schedule (default 10)")
    p.add_argument("--reps", type=int, default=2,
                   help="op applications fused per timed call (amortizes "
                        "dispatch; default 2 — naive schedules on irregular "
                        "topologies chain O(n) ppermutes per application, "
                        "and XLA compile time grows with the chain)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--smoke", action="store_true",
                   help="tiny fast configuration for CI (n=8, few iters)")
    p.add_argument("--transport", action="store_true",
                   help="run the window-transport loopback microbench "
                        "(coalescing on vs off) instead of the schedule "
                        "bench; asserts the >= 2x messages/s win")
    p.add_argument("--transport-smoke", action="store_true",
                   help="tiny CI variant of --transport: asserts batched "
                        "delivery + metric presence, no timing assertion")
    p.add_argument("--ffi", action="store_true",
                   help="run the zero-copy XLA put-path microbench "
                        "(BLUEFOG_TPU_WIN_XLA): put-side dispatch us/row "
                        "and msgs/s of the legacy / PR-9 native / FFI "
                        "window put paths through a loopback store; "
                        "asserts the >= 2x dispatch win for rows >= 4 KiB "
                        "and zero staging-copy bytes on the FFI leg")
    p.add_argument("--ffi-smoke", action="store_true",
                   help="tiny CI variant of --ffi (`make ffi-smoke`): "
                        "asserts the FFI path engaged + zero staging-copy "
                        "bytes, no timing assertion; graceful skip when "
                        "jax.ffi or the native bf_xla symbols are absent")
    p.add_argument("--fused", action="store_true",
                   help="run the whole-step compilation bench "
                        "(BLUEFOG_TPU_FUSED_STEP): eager vs fused "
                        "end-to-end step time on the loopback transport "
                        "rig plus the structural gates; asserts the "
                        ">= 1.5x step-time win and <= 1e-6 trajectory "
                        "equivalence over 50 steps")
    p.add_argument("--fused-smoke", action="store_true",
                   help="CI variant of --fused (`make fused-smoke`): "
                        "asserts fused engagement (bf_fused_step_active, "
                        "in-program puts counted), trajectory equivalence "
                        "vs eager, FUSED_STEP=0 bitwise inertness and the "
                        "graceful one-warning fallback; no timing "
                        "assertion, graceful skip without the native "
                        "bf_xla_win_put_pass handler")
    p.add_argument("--probe-smoke", action="store_true",
                   help="CI gate of the in-program probes "
                        "(`make probe-smoke`): a fused loopback run with "
                        "BLUEFOG_TPU_PROBE on (the default) asserts the "
                        "probe surfaces land — bf_fused_overlap_ratio in "
                        "(0, 1], per-bucket issue histograms, "
                        "bf_probe_events_total, a finite measured-vs-"
                        "modeled divergence — and that trace-merge emits "
                        "valid JSON carrying the fused-probe lanes; "
                        "graceful skip when the native core lacks "
                        "bf_xla_probe")
    p.add_argument("--async-smoke", action="store_true",
                   help="structural CI gate of the barrier-free async "
                        "gossip mode (`make async-smoke`): a loopback "
                        "two-transport rig drives real accumulates whose "
                        "origin-step clock is pinned behind the "
                        "receiver's (the injected delay), asserts the "
                        "bounded-staleness fold rejected them into the "
                        "stale-residual store with the counters on "
                        "/metrics + the async /healthz block, that "
                        "win_fold_stale_residuals restores mass exactly, "
                        "and that BLUEFOG_TPU_TELEMETRY=0 leaves the "
                        "registry untouched")
    p.add_argument("--tracerec-smoke", action="store_true",
                   help="CI gate of message-level tracing "
                        "(`make tracerec-smoke`): flight recorder on + "
                        "sampled wire trace tags through a loopback "
                        "window-store pair — asserts the per-edge "
                        "contribution-age histograms appear on /metrics "
                        "and /healthz, the recorder dump decodes into a "
                        "valid merged trace with flow arrows, and the "
                        "BLUEFOG_TPU_TELEMETRY=0 zero-mutation guard")
    p.add_argument("--stripe-smoke", action="store_true",
                   help="CI gate of the multi-stream striped transport "
                        "(`make stripe-smoke`): asserts >= 2 stripes "
                        "engage on the loopback rig with per-stripe "
                        "telemetry present, and that a pinned "
                        "BLUEFOG_TPU_WIN_STRIPES=1 leg reproduces the "
                        "pre-stripe wire behavior exactly")
    p.add_argument("--rows", type=int, default=5000,
                   help="transport bench: messages per mode (default 5000)")
    p.add_argument("--row-bytes", type=int, default=4096,
                   help="transport bench: payload bytes per message "
                        "(default 4096 — the small-gossip-row regime)")
    p.add_argument("--placement", action="store_true",
                   help="run the physical-placement cost-model report "
                        "(modeled link-load naive vs optimized across "
                        "ring/Exp2/star/random-regular on simulated 4x8 "
                        "and 8x8 tori) plus an end-to-end output-"
                        "equivalence check on the virtual CPU mesh")
    p.add_argument("--placement-smoke", action="store_true",
                   help="CI variant of --placement (same assertions, "
                        "same tori — the cost model is pure host math)")
    p.add_argument("--placement-iters", type=int, default=1000,
                   help="simulated-annealing refinement iterations for "
                        "the placement search (default 1000)")
    p.add_argument("--hier", action="store_true",
                   help="run the hierarchical-gossip report: per-step DCN "
                        "wire rows, modeled inter-slice serial link time "
                        "and simulated consensus distance of flat exp2 vs "
                        "the two-level mode on simulated 2x(4x8) and "
                        "4x(4x4) multi-slice tori, plus an end-to-end "
                        "product-topology equivalence check on the "
                        "virtual CPU mesh; asserts >= 4x DCN reduction "
                        "at equal-or-better consensus")
    p.add_argument("--hier-smoke", action="store_true",
                   help="CI variant of --hier (same assertions — the "
                        "cost model and consensus simulation are pure "
                        "host math)")
    p.add_argument("--synth", action="store_true",
                   help="run the schedule-synthesis report: modeled "
                        "serial_link_time naive / congestion-packed / "
                        "synthesized across ring/Exp2/star/random-regular "
                        "on simulated 4x8, 8x8 and multi-slice tori, plus "
                        "an end-to-end output-equivalence check of a "
                        "synthesized schedule on the virtual CPU mesh")
    p.add_argument("--synth-smoke", action="store_true",
                   help="CI variant of --synth (same assertions — the "
                        "cost model is pure host math)")
    p.add_argument("--sharded", action="store_true",
                   help="run the sharded-gossip report: simulated MoE "
                        "trees at 25/50/75%% replicated fraction assert "
                        "per-step DCN bytes scale with the replicated "
                        "fraction only (sharded slices never cross "
                        "replica groups), plus an executor leg on the "
                        "8-device CPU mesh checking the dense oracle, "
                        "the per-shard telemetry split and the "
                        "BLUEFOG_TPU_SHARDED_GOSSIP=0 bitwise hatch")
    p.add_argument("--sharded-smoke", action="store_true",
                   help="CI variant of --sharded (same assertions — "
                        "`make sharded-smoke`)")
    return p.parse_args()


def _transport_one_mode(mode: str, rows: int, row_bytes: int,
                        peers: int = 1, stripes: int = 1,
                        windows: int = 8, trace_every: int = 0,
                        recorder: bool = False) -> dict:
    """Loopback exchange of ``peers x rows`` messages in one mode.

    Modes: ``legacy`` (per-message blocking sends, coalescing off),
    ``python`` (PR-4 coalesced path: Python sender workers + batched
    drain, ``BLUEFOG_TPU_WIN_NATIVE=0``) and ``native`` (the C++ hot
    path: per-peer queues, frame encode, drain decode + fold all in
    ``winsvc.cc``).  ``peers`` distinct client transports feed ONE server
    round-robin — N TCP connections, N reader threads, interleaved
    frames: the drain-side concurrency axis.  (One producer thread drives
    them all: N Python sender threads would measure GIL convoying, not
    the receive path.)

    End-to-end timing: the clock stops when the LAST message has been
    applied at the receiver, so the drain side (per-message Python apply
    vs vectorized batch apply vs native fold) is part of what's measured
    — exactly the halves the tentpole moved to C++.  Returns rates plus
    the server's drain-burst p50/p99 for the run."""
    import threading

    import numpy as np

    from bluefog_tpu.ops.transport import (OP_ACCUMULATE, OP_TRACE_FLAG,
                                           WindowTransport, make_trace_tag)
    from bluefog_tpu.utils import config, flightrec, telemetry

    prev_native = os.environ.get("BLUEFOG_TPU_WIN_NATIVE")
    prev_coalesce = os.environ.get("BLUEFOG_TPU_WIN_COALESCE")
    prev_stripes = os.environ.get("BLUEFOG_TPU_WIN_STRIPES")
    prev_trace = os.environ.get("BLUEFOG_TPU_TRACE_SAMPLE")
    os.environ["BLUEFOG_TPU_WIN_COALESCE"] = \
        "0" if mode == "legacy" else "1"
    os.environ["BLUEFOG_TPU_WIN_NATIVE"] = \
        "1" if mode == "native" else "0"
    os.environ["BLUEFOG_TPU_WIN_STRIPES"] = str(max(1, stripes))
    if trace_every > 0:
        os.environ["BLUEFOG_TPU_TRACE_SAMPLE"] = str(trace_every)
    else:
        os.environ.pop("BLUEFOG_TPU_TRACE_SAMPLE", None)
    # Long linger: the bench flushes explicitly (as window ops do at op
    # boundaries), so batch sizes reflect the queue, not the clock.
    os.environ.setdefault("BLUEFOG_TPU_WIN_COALESCE_LINGER_MS", "5")
    config.reload()
    telemetry.reset()  # per-mode isolation for the drain histograms

    state = {"n": 0, "batches": 0}
    done = threading.Event()
    target = [0]
    lock = threading.Lock()

    def count(k):
        with lock:
            state["n"] += k
            if state["n"] >= target[0]:
                done.set()

    def apply(op, name, src, dst, weight, p_weight, payload):
        count(1)

    def apply_batch(msgs):
        state["batches"] += 1
        count(len(msgs))

    def apply_items(items):
        n = 0
        for kind, payload in items:
            n += (payload[5] + payload[6]) if kind else 1
        count(n)

    server = WindowTransport(apply, apply_batch=apply_batch,
                             apply_items=apply_items, drain_interval=0.0005)
    # Several windows + rotating src ranks so the (window, row) shard
    # actually spreads across stripes (one window/one row would pin a
    # single stripe and measure nothing).
    names = [f"bench{w}" for w in range(max(1, windows))]
    for nm in names:
        server.register_window(nm, row_bytes // 4)
    clients = [WindowTransport(lambda *a: None) for _ in range(peers)]
    if recorder:
        flightrec.enable()
        flightrec.reset()  # this cell's events only
    try:
        row = np.arange(row_bytes // 4, dtype=np.float32)
        row_blob = row.tobytes()
        host, port = "127.0.0.1", server.port
        nw = len(names)

        def payload_for(i):
            # Sampled wire trace tag, exactly as the window layer appends
            # it (the 1-in-N tobytes+concat IS the sender-side tagging
            # cost this cell measures).
            tag = make_trace_tag(i % 8)
            if tag is None:
                return OP_ACCUMULATE, row
            return (OP_ACCUMULATE | OP_TRACE_FLAG,
                    np.frombuffer(row_blob + tag, np.uint8))

        def exchange(count_per_client):
            done.clear()
            total = count_per_client * peers
            target[0] = state["n"] + total
            if state["n"] >= target[0]:
                done.set()
            t0 = time.perf_counter()
            if trace_every > 0:
                sends = [c.send for c in clients]
                for i in range(total):
                    op, payload = payload_for(i)
                    sends[i % peers](host, port, op, names[i % nw],
                                     i % 8, 1, 1.0, payload)
            elif peers == 1:
                send = clients[0].send
                for i in range(count_per_client):
                    send(host, port, OP_ACCUMULATE, names[i % nw],
                         i % 8, 1, 1.0, row)
            else:
                sends = [c.send for c in clients]
                for i in range(total):
                    sends[i % peers](host, port, OP_ACCUMULATE,
                                     names[i % nw], i % 8, 1, 1.0, row)
            for c in clients:
                c.flush()
            assert done.wait(timeout=300), \
                f"only {state['n']}/{target[0]} messages arrived"
            return time.perf_counter() - t0

        exchange(min(rows // 10 + 1, 200))  # warm the connection pool
        dt = exchange(rows)
        total = rows * peers
        for c in clients:
            c.stop()
        server.stop()  # final telemetry pump before the histogram read
        clients.clear()
        burst = telemetry.histogram_percentiles(
            "bf_win_drain_burst_seconds", qs=(50.0, 99.0)) or {}
        snap = telemetry.snapshot() if telemetry.enabled() else {}
        engaged = {k.split('stripe="', 1)[1].split('"', 1)[0]
                   for k in snap
                   if k.startswith("bf_win_tx_stripe_bytes_total")}
        res = {
            "mode": mode,
            "peers": peers,
            "stripes": stripes,
            "stripes_engaged": len(engaged),
            "row_bytes": row_bytes,
            "native_engaged": bool(server.native_path),
            "decode_threads": int(getattr(server, "decode_threads", 0)),
            "msgs_per_s": round(total / dt, 1),
            "mb_per_s": round(total * row_bytes / dt / 1e6, 2),
            "batches_seen": state["batches"],
            "drain_burst_p50_ms": round(burst.get(50.0, 0.0) * 1e3, 3),
            "drain_burst_p99_ms": round(burst.get(99.0, 0.0) * 1e3, 3),
        }
        if recorder:
            # Per-edge one-way delay (enqueue → drain decode) from the
            # flight-recorder events — sender and receiver share this
            # process, so one pseudo-dump at offset 0 joins both ends.
            from bluefog_tpu.tools import tracegossip
            ev = flightrec.snapshot()
            delays = tracegossip.edge_delays(
                [{"rank": 0, "offset_us": 0, "events": ev}])
            res["tracing"] = {
                "rec_events": int(len(ev)),
                "sample_every": trace_every,
                "edges": {f"{s}->{d}": {
                    "tags": int(len(v)),
                    "p50_ms": round(float(np.percentile(v, 50)) / 1e3, 3),
                    "p99_ms": round(float(np.percentile(v, 99)) / 1e3, 3)}
                    for (s, d), v in delays.items()},
            }
        return res
    finally:
        for c in clients:
            c.stop()
        try:
            server.stop()
        except Exception:  # noqa: BLE001 — double-stop after success path
            pass
        for var, prev in (("BLUEFOG_TPU_WIN_NATIVE", prev_native),
                          ("BLUEFOG_TPU_WIN_COALESCE", prev_coalesce),
                          ("BLUEFOG_TPU_WIN_STRIPES", prev_stripes),
                          ("BLUEFOG_TPU_TRACE_SAMPLE", prev_trace)):
            if prev is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = prev
        config.reload()


def transport_main(args) -> int:
    """Loopback transport microbench (and the `make transport-smoke` CI
    gate): the small-row size sweep (64 B / 256 B / 4 KB) across the
    legacy / Python-coalesced / native paths, plus a concurrent-peers
    axis on the native path.  The full variant asserts the native hot
    path's >= 5x messages/s win over the PR-4 Python coalesced path for
    <= 256 B rows (10x target); the smoke variant asserts structure only
    (batched delivery happened, the native path actually engaged when
    available, the telemetry series exist) — shared CI boxes jitter too
    much for timing gates."""
    import sys

    from bluefog_tpu import native
    from bluefog_tpu.utils import telemetry

    smoke = args.transport_smoke
    rows = min(args.rows, 300) if smoke else args.rows
    if not native.available():
        print(json.dumps({
            "metric": "win_transport_native_speedup",
            "value": None, "unit": "x", "status": "no_native",
            "detail": {"reason": "native core not built"}}))
        return 0 if smoke else 2
    # An explicit BLUEFOG_TPU_WIN_NATIVE=0 in the launch environment pins
    # the whole run to the Python fallback (the `make transport-smoke`
    # native-off leg): the native modes are skipped, nothing native is
    # asserted — the same behavior as a host whose .so lacks the symbols.
    native_ok = (native.has_win_native()
                 and os.environ.get("BLUEFOG_TPU_WIN_NATIVE") != "0")

    sizes = [64, 256, 4096]
    modes = ["python"] + (["native"] if native_ok else [])
    sweep = []
    failures = []

    # Legacy reference at the CLI row size (fewer rows: one blocking RPC
    # per message is ~15x slower) — the PR-4 coalesce ratio stays visible
    # in the trajectory.
    legacy = _transport_one_mode("legacy", max(rows // 4, 50),
                                 args.row_bytes)
    if legacy["batches_seen"] != 0:
        failures.append("legacy path delivered batch frames")

    for row_bytes in sizes:
        for mode in modes:
            res = _transport_one_mode(mode, rows, row_bytes)
            sweep.append(res)
            if mode == "python" and res["batches_seen"] == 0:
                failures.append(
                    f"python coalescing on but no batch frame arrived "
                    f"({row_bytes} B)")
            if mode == "native" and not res["native_engaged"]:
                failures.append(
                    f"native path available but did not engage "
                    f"({row_bytes} B)")

    # Telemetry presence (from the LAST run's registry — reset per mode):
    # the batch series must exist on whichever path ran last.
    snap = telemetry.snapshot() if telemetry.enabled() else {}
    for series in ("bf_win_tx_batches_total", "bf_win_tx_batched_msgs_total",
                   "bf_win_tx_batch_size", "bf_win_rx_batches_total"):
        if not any(k.startswith(series) for k in snap):
            failures.append(f"expected telemetry series {series!r}")
    if native_ok:
        for series in ("bf_win_native_tx_frames_total",
                       "bf_win_native_rx_frames_total"):
            if not any(k.startswith(series) for k in snap):
                failures.append(
                    f"native path engaged but series {series!r} missing")

    # Concurrent-peers axis (drain-side scaling): p99 drain burst should
    # stay flat as senders multiply — the folded commit path does per-RUN
    # Python work, not per-message.
    peer_axis = [1, 2] if smoke else [1, 4, 8]
    peers_tbl = []
    if native_ok:
        for p in peer_axis:
            peers_tbl.append(_transport_one_mode(
                "native", max(rows // p, 50), 256, peers=p))

    # Stripe axis (multi-stream transport): 1/2/4 stripes x 4 KiB/64 KiB/
    # 256 KiB rows x 1/8 concurrent peers on the native path — the
    # regime where a single fat link is bounded by one stream.  Reported
    # as msgs/s + drain p99 per cell; the headline ratio is best-striped
    # vs single-stream at >= 64 KiB rows under 8 peers.
    stripe_tbl = []
    stripe_speedup = None
    if native_ok and not smoke:
        for row_bytes in (4096, 65536, 262144):
            # Scale the message count down with the row so every cell
            # moves a comparable byte volume.
            per = max(80, int(rows * 4096 / max(row_bytes, 4096)))
            for p in (1, 8):
                for st in (1, 2, 4):
                    stripe_tbl.append(_transport_one_mode(
                        "native", max(per // p, 40), row_bytes, peers=p,
                        stripes=st))

        def _cell(row_bytes, p, st):
            for r in stripe_tbl:
                if (r["row_bytes"], r["peers"], r["stripes"]) == \
                        (row_bytes, p, st):
                    return r
            return None

        ratios_sp = []
        for row_bytes in (65536, 262144):
            base = _cell(row_bytes, 8, 1)
            cands = [c for c in (_cell(row_bytes, 8, s) for s in (2, 4))
                     if c]
            if base and cands:
                best = max(cands, key=lambda c: c["msgs_per_s"])
                ratios_sp.append(best["msgs_per_s"] / base["msgs_per_s"])
        if ratios_sp:
            stripe_speedup = round(max(ratios_sp), 2)

    def _rate(mode, row_bytes):
        for r in sweep:
            if r["mode"] == mode and r["row_bytes"] == row_bytes:
                return r["msgs_per_s"]
        return None

    ratios = {}
    for row_bytes in sizes:
        py, nat = _rate("python", row_bytes), _rate("native", row_bytes)
        if py and nat:
            ratios[row_bytes] = round(nat / py, 2)
    small_ratio = max((v for k, v in ratios.items() if k <= 256),
                      default=None)

    # FFI leg (full runs only — it needs jax + the loopback store): the
    # zero-copy XLA put path vs the PR-9 native and legacy Python put
    # paths, folded into this report's detail.  Capability-gated with a
    # graceful skip, like every other degraded mode here.
    ffi_detail = None
    ffi_value = None
    if not smoke and native_ok:
        from bluefog_tpu import _compat
        from bluefog_tpu import native as _native
        if _native.has_win_xla() and _compat.jax_ffi() is not None \
                and os.environ.get("BLUEFOG_TPU_WIN_XLA") != "0":
            os.environ.setdefault("JAX_PLATFORMS", "cpu")
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count=8")
            # armed() is the full capability check (it also catches a
            # non-CPU jax backend, where auto-disarm is the documented
            # degraded mode): skip, never fail, when it says no.
            from bluefog_tpu.ops import xlaffi as _xlaffi
            if _xlaffi.armed():
                ffi_value, ffi_detail, ffi_failures = _ffi_report(
                    smoke=False)
                failures.extend(f"ffi leg: {f}" for f in ffi_failures)
            else:
                ffi_detail = {"skipped": _xlaffi.disarm_reason()}
        else:
            ffi_detail = {"skipped": "jax.ffi or bf_xla symbols absent"}

    # Tracing leg — LAST, because arming the flight recorder is
    # process-sticky and must not touch the cells above.  Two readouts:
    # the 4 KiB / 8-peer overhead pair (recorder on + 1/64 sampled trace
    # tags vs plain — the acceptance cell for the <= 2% regression bound
    # on real hardware; reported, not asserted, on shared CI boxes) and
    # the per-edge one-way-delay p50/p99 from every-message tags
    # (detail.tracing — the direct per-link latency sensor that confirms
    # the PR-11 stripe win on the restored multi-host rig).
    tracing_detail = None
    if native_ok:
        t_rows = max(rows // 8, 50)
        base = _transport_one_mode("native", t_rows, 4096, peers=8)
        traced = _transport_one_mode("native", t_rows, 4096, peers=8,
                                     trace_every=64, recorder=True)
        delay_leg = _transport_one_mode("native", max(t_rows // 2, 50),
                                        4096, peers=2, trace_every=1,
                                        recorder=True)
        tracing_detail = {
            "overhead_cell": {
                "row_bytes": 4096, "peers": 8, "sample_every": 64,
                "base_msgs_per_s": base["msgs_per_s"],
                "traced_msgs_per_s": traced["msgs_per_s"],
                "ratio": round(traced["msgs_per_s"]
                               / max(base["msgs_per_s"], 1e-9), 3),
            },
        }
        tracing_detail.update(delay_leg.get("tracing", {}))
        if not delay_leg.get("tracing", {}).get("edges"):
            failures.append(
                "tracing leg produced no per-edge delay readout")

    # Link-observatory leg — the same rig, judged on the ONLINE estimator
    # (utils/linkobs.py).  Two readouts next to detail.tracing:
    #   (a) the overhead pair: the traced 4 KiB / 8-peer cell with
    #       BLUEFOG_TPU_LINK_OBS=0 vs 1 — the acceptance bound (<= 2% on
    #       quiet hardware) is reported, not asserted, on shared CI
    #       boxes; the OFF cell is asserted bitwise inert (not one
    #       bf_link_* series), the ON cell must publish tx goodput;
    #   (b) the flight recorder's per-edge delay samples fed through
    #       linkobs.note_delay (the loopback rig bypasses the window
    #       commit path that feeds the estimator in-process), reported
    #       as the same link table bf.link_report() serves.
    links_detail = None
    if native_ok and tracing_detail is not None:
        from bluefog_tpu.tools import tracegossip
        from bluefog_tpu.utils import config, flightrec, linkobs, telemetry
        prev_obs = os.environ.get("BLUEFOG_TPU_LINK_OBS")
        # The goodput gauge publishes once per >= 0.5 s rate window —
        # longer than a whole smoke cell.  Shrink the window (read at
        # call time) so the ON cell publishes deterministically.
        prev_win = linkobs._GOODPUT_WINDOW_S
        try:
            os.environ["BLUEFOG_TPU_LINK_OBS"] = "0"
            off = _transport_one_mode("native", t_rows, 4096, peers=8,
                                      trace_every=64)
            snap = telemetry.snapshot() if telemetry.enabled() else {}
            inert = not any(k.startswith("bf_link_") for k in snap)
            if not inert:
                failures.append(
                    "BLUEFOG_TPU_LINK_OBS=0 leg still published bf_link_* "
                    "series (the off-switch is not bitwise inert)")
            os.environ["BLUEFOG_TPU_LINK_OBS"] = "1"
            linkobs._GOODPUT_WINDOW_S = 0.02
            on = _transport_one_mode("native", t_rows, 4096, peers=8,
                                     trace_every=64)
            snap = telemetry.snapshot() if telemetry.enabled() else {}
            if not any(k.startswith("bf_link_goodput_bytes")
                       for k in snap):
                failures.append(
                    "link observatory armed but the tx path published no "
                    "bf_link_goodput_bytes series")
            linkobs.reset()
            delays = tracegossip.edge_delays(
                [{"rank": 0, "offset_us": 0,
                  "events": flightrec.snapshot()}])
            for (s, d), samples in sorted(delays.items()):
                for us in samples:
                    linkobs.note_delay(int(s), int(d), float(us))
            rep = linkobs.local_report()
            if not rep.get("edges"):
                failures.append(
                    "link observatory produced no edge table from the "
                    "recorder's delay samples")
            links_detail = {
                "overhead_cell": {
                    "row_bytes": 4096, "peers": 8, "sample_every": 64,
                    "off_msgs_per_s": off["msgs_per_s"],
                    "on_msgs_per_s": on["msgs_per_s"],
                    "ratio": round(on["msgs_per_s"]
                                   / max(off["msgs_per_s"], 1e-9), 3),
                    "off_inert": inert,
                },
                "report": rep,
            }
        finally:
            linkobs._GOODPUT_WINDOW_S = prev_win
            linkobs.reset()
            if prev_obs is None:
                os.environ.pop("BLUEFOG_TPU_LINK_OBS", None)
            else:
                os.environ["BLUEFOG_TPU_LINK_OBS"] = prev_obs
            config.reload()

    # Whole-step compilation leg (full runs only — it needs jax + the
    # native XLA put handler): the eager-vs-fused end-to-end step-time
    # cell, folded into this report's detail next to the links leg.
    # Capability-gated with a graceful skip, like the ffi leg above.
    fused_detail = None
    if not smoke and native_ok:
        from bluefog_tpu import native as _native
        if (_native.has_win_xla() and _native.has_xla_handler()
                and os.environ.get("BLUEFOG_TPU_FUSED_STEP") != "0"):
            prev_fused = _fused_env_setup()
            try:
                from bluefog_tpu.ops import xlaffi as _xlaffi
                from bluefog_tpu.utils import config as _fconfig
                _fconfig.reload()
                _xlaffi._reset_for_tests()
                if _xlaffi.armed() and _xlaffi.has_passthrough():
                    fused_detail = _fused_timing_cell()
                else:
                    fused_detail = {"skipped": _xlaffi.disarm_reason()
                                    or "no passthrough put handler"}
            finally:
                _fused_env_restore(prev_fused)
        else:
            fused_detail = {"skipped": "bf_xla symbols absent or "
                                       "FUSED_STEP pinned off"}

    rc = 0
    for f in failures:
        print(f"bench_comm --transport: {f}", file=sys.stderr)
        rc = 1
    if not smoke and native_ok and (small_ratio is None
                                    or small_ratio < 5.0):
        print(f"bench_comm: native transport speedup {small_ratio}x < 5x "
              "for <=256 B rows", file=sys.stderr)
        rc = 1
    print(json.dumps({
        "metric": "win_transport_native_speedup",
        "value": small_ratio,
        "unit": "x",
        "detail": {
            "rows": rows,
            "smoke": smoke,
            "native_available": native_ok,
            "ratios_by_row_bytes": ratios,
            "legacy": legacy,
            "sweep": sweep,
            "peers": peers_tbl,
            "stripes": stripe_tbl,
            "stripe_speedup_64k_plus_8p": stripe_speedup,
            "ffi_dispatch_speedup": ffi_value,
            "ffi": ffi_detail,
            "tracing": tracing_detail,
            "links": links_detail,
            "fused_step": fused_detail,
        },
    }))
    return rc


def stripe_main(args) -> int:
    """`make stripe-smoke`: the multi-stream striped transport CI gate.

    Three structural assertions, no timing (shared CI boxes jitter):
      1. a 2-stripe loopback run actually engages >= 2 stripes (distinct
         per-stripe telemetry series carried bytes) and, on the native
         path, the drain decode pool is live with its busy gauge present;
      2. per-stripe series exist: `bf_win_tx_stripe_bytes_total` and the
         (peer, stripe)-labeled `bf_win_tx_queue_depth` gauges;
      3. a pinned BLUEFOG_TPU_WIN_STRIPES=1 leg reproduces the pre-stripe
         wire exactly — one sender, send-order delivery with identical
         fields and payload bytes, fence weight 0.0.
    """
    import sys
    import threading

    import numpy as np

    from bluefog_tpu import native
    from bluefog_tpu.utils import telemetry

    if not native.available():
        print(json.dumps({
            "metric": "win_transport_stripes_engaged",
            "value": None, "unit": "stripes", "status": "no_native",
            "detail": {"reason": "native core not built"}}))
        return 0
    native_ok = (native.has_win_native()
                 and os.environ.get("BLUEFOG_TPU_WIN_NATIVE") != "0")
    failures = []

    # -- leg 1: striped run, >= 2 stripes engaged + telemetry ---------------
    mode = "native" if native_ok else "python"
    res = _transport_one_mode(mode, 300, 4096, peers=2, stripes=2)
    if res["stripes_engaged"] < 2:
        failures.append(
            f"only {res['stripes_engaged']} stripe(s) engaged with "
            "BLUEFOG_TPU_WIN_STRIPES=2")
    if native_ok and not res["native_engaged"]:
        failures.append("native path available but did not engage")
    snap = telemetry.snapshot() if telemetry.enabled() else {}
    for series in ("bf_win_tx_stripe_bytes_total",):
        stripes_seen = {k.split('stripe="', 1)[1].split('"', 1)[0]
                        for k in snap if k.startswith(series)}
        if len(stripes_seen) < 2:
            failures.append(
                f"expected >= 2 stripe labels on {series!r}, "
                f"got {sorted(stripes_seen)}")
    if not any(k.startswith("bf_win_tx_queue_depth") and 'stripe="' in k
               for k in snap):
        failures.append("per-stripe bf_win_tx_queue_depth gauges missing")
    if native_ok and res["decode_threads"] > 0 and not any(
            k.startswith("bf_win_rx_decode_pool_busy") for k in snap):
        failures.append("bf_win_rx_decode_pool_busy gauge missing with a "
                        "live decode pool")

    # -- leg 2: STRIPES=1 pinned — the pre-stripe wire, exactly -------------
    from bluefog_tpu.ops import transport as T
    from bluefog_tpu.ops import window as W
    from bluefog_tpu.utils import config as _config
    prev = {v: os.environ.get(v) for v in
            ("BLUEFOG_TPU_WIN_STRIPES", "BLUEFOG_TPU_WIN_NATIVE",
             "BLUEFOG_TPU_WIN_COALESCE_LINGER_MS")}
    os.environ["BLUEFOG_TPU_WIN_STRIPES"] = "1"
    os.environ["BLUEFOG_TPU_WIN_NATIVE"] = "0"
    os.environ["BLUEFOG_TPU_WIN_COALESCE_LINGER_MS"] = "2"
    _config.reload()
    got = []
    cv = threading.Condition()

    def apply(op, name, src, dst, weight, p_weight, payload):
        with cv:
            got.append((op, name, src, dst, weight, bytes(payload)))
            cv.notify_all()

    def apply_batch(msgs):
        for m in msgs:
            apply(*m)

    server = T.WindowTransport(apply, apply_batch=apply_batch)
    client = T.WindowTransport(lambda *a: None)
    try:
        if client.n_stripes != 1:
            failures.append(
                f"STRIPES=1 leg resolved {client.n_stripes} stripes")
        host, port = "127.0.0.1", server.port
        expect = []
        for i in range(8):
            row = np.arange(16, dtype=np.float32) * (i + 1)
            client.send(host, port, T.OP_PUT, "w", i, 1, 0.5, row)
            expect.append((T.OP_PUT, "w", i, 1, 0.5, row.tobytes()))
        client.send(host, port, T.OP_FENCE_REQ, "", 0, -1,
                    W._fanout_weight(1), np.zeros(0, np.float32))
        expect.append((T.OP_FENCE_REQ, "", 0, -1, 0.0, b""))
        client.flush()
        with cv:
            ok = cv.wait_for(lambda: len(got) >= len(expect), timeout=30)
        if not ok or got != expect:
            failures.append(
                "STRIPES=1 wire differs from the pre-stripe transport "
                f"(got {len(got)} messages)")
        if sorted(k[2] for k in client._senders) not in ([], [0]):
            failures.append("STRIPES=1 leg created stripe senders > 0")
    finally:
        client.stop()
        server.stop()
        for var, val in prev.items():
            if val is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = val
        _config.reload()

    rc = 0
    for f in failures:
        print(f"bench_comm --stripe-smoke: {f}", file=sys.stderr)
        rc = 1
    print(json.dumps({
        "metric": "win_transport_stripes_engaged",
        "value": res["stripes_engaged"],
        "unit": "stripes",
        "detail": {
            "native_available": native_ok,
            "striped_cell": res,
            "single_stripe_wire_ok": all(
                "STRIPES=1" not in f for f in failures),
        },
    }))
    return rc


def async_main(args) -> int:
    """`make async-smoke`: the barrier-free async gossip CI gate.

    Structural assertions, no timing — a loopback two-transport rig
    (real win_accumulate through the real coalesced/native drain path)
    with the async mode armed:
      1. a FRESH round (origin-step clock == receiver clock) commits
         into staging on the exact legacy arithmetic path;
      2. a STALE round — the sender's origin-step clock pinned behind
         the receiver's (the injected delay: exactly what a straggler's
         gossip looks like on the wire) — is rejected into the
         stale-residual store, with `bf_win_stale_rejected_total{src}`
         on /metrics and the "async" block (step, lag, policy) in
         /healthz;
      3. win_fold_stale_residuals folds the held mass back into staging
         EXACTLY (wire + residual + folded == input, the conservation
         invariant, proven on real wire frames);
      4. a BLUEFOG_TPU_TELEMETRY=0 leg runs the same traffic with the
         registry left completely untouched (the policy still applies —
         it is state, not telemetry).
    """
    import sys
    import threading
    import urllib.error
    import urllib.request

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8")
    prev = {v: os.environ.get(v) for v in (
        "BLUEFOG_TPU_ASYNC", "BLUEFOG_TPU_ASYNC_STALENESS_STEPS",
        "BLUEFOG_TPU_ASYNC_STALENESS_POLICY", "BLUEFOG_TPU_TRACE_SAMPLE",
        "BLUEFOG_TPU_TELEMETRY", "BLUEFOG_TPU_WIN_COALESCE_LINGER_MS")}
    os.environ.update({
        "BLUEFOG_TPU_ASYNC": "1",
        "BLUEFOG_TPU_ASYNC_STALENESS_STEPS": "4",
        "BLUEFOG_TPU_ASYNC_STALENESS_POLICY": "reject",
        "BLUEFOG_TPU_TRACE_SAMPLE": "1",
        "BLUEFOG_TPU_TELEMETRY": "1",
        "BLUEFOG_TPU_WIN_COALESCE_LINGER_MS": "100",
    })
    import numpy as np

    import bluefog_tpu as bf
    from bluefog_tpu import topology as topo
    from bluefog_tpu.ops import transport as T
    from bluefog_tpu.ops import window as W
    from bluefog_tpu.utils import config as _config
    from bluefog_tpu.utils import telemetry
    _config.reload()
    failures = []
    bf.init(lambda: topo.RingGraph(8))
    telemetry.reset()

    def drive(rounds):
        """Real accumulate streams through the loopback store; each round
        is (origin_step, rows 8xD).  Returns the committed window state.
        The window is created pre-directory so one store serves both
        wire ends (the tracerec/test_win_xla pattern)."""
        applied = [0]
        cv = threading.Condition()

        def bump(k):
            with cv:
                applied[0] += k
                cv.notify_all()

        def apply(op, name, src, dst, weight, p_weight, payload):
            W._apply_inbound(op, name, src, dst, weight, p_weight, payload)
            bump(1)

        def apply_batch(msgs):
            W._apply_inbound_batch(msgs)
            bump(len(msgs))

        def apply_items(items):
            W._apply_inbound_items(items)
            bump(sum((p[5] + p[6]) if k else 1 for k, p in items))

        server = T.WindowTransport(apply, apply_batch=apply_batch,
                                   apply_items=apply_items)
        client = T.WindowTransport(lambda *a: None)
        saved = W._store.distrib
        try:
            assert bf.win_create(np.zeros((8, 6), np.float32), "asmoke",
                                 zero_init=True)
            server.register_window("asmoke", 6)
            W._store.distrib = W._Distrib(
                client, rank_owner={r: r % 2 for r in range(8)},
                proc_addr={0: ("127.0.0.1", 1),
                           1: ("127.0.0.1", server.port)},
                my_proc=0)
            W.configure_async()
            # The receiver's step clock: contributions age against it.
            W.set_async_step(100)
            total = 0
            for origin_step, t in rounds:
                # Injected delay: pin the SENDER-side origin-step clock
                # (both encoders) behind the receiver's — each tag now
                # says "I was computed at step <origin_step>".
                T.set_trace_origin_step(origin_step)
                bf.win_accumulate(t, "asmoke")
                total += 8  # the ring's 8 remote (even->odd) edges
                with cv:
                    assert cv.wait_for(lambda: applied[0] >= total,
                                       timeout=30), (applied[0], total)
            win = W._store.get("asmoke")
            with win.lock:
                return (
                    {k: v.copy() for k, v in win.staging.items()},
                    {k: v.copy() for k, v in win.stale_residual.items()},
                    W.win_fold_stale_residuals("asmoke"),
                    {k: v.copy() for k, v in win.staging.items()},
                )
        finally:
            W._store.distrib = saved
            bf.win_free("asmoke")
            client.stop()
            server.stop()

    fresh = np.random.RandomState(5).randn(8, 6).astype(np.float32)
    stale = np.random.RandomState(6).randn(8, 6).astype(np.float32)
    staging, residual, folded, after = drive(
        [(99, fresh), (50, stale)])    # ages 1 (fresh) and 50 (stale)
    # The ring's 8 remote (even-src -> odd-dst) edges, wraparound included.
    remote = sorted({((s + step) % 8, s)
                     for s in range(0, 8, 2) for step in (1, -1)})
    n_stale_edges = 0
    for key in remote:
        d, s = key
        exp_fresh = fresh[s]
        exp_stale = stale[s]
        if not np.array_equal(staging.get(key), exp_fresh):
            failures.append(f"edge {key}: fresh round not committed "
                            "on the legacy path")
        if key in residual:
            n_stale_edges += 1
            if not np.array_equal(residual[key], exp_stale):
                failures.append(f"edge {key}: stale residual mismatch")
        if not np.array_equal(after.get(key), exp_fresh + exp_stale):
            failures.append(f"edge {key}: fold did not restore mass "
                            "exactly")
    if n_stale_edges == 0:
        failures.append("no edge ever hit the staleness policy")

    # -- /metrics + /healthz surfaces ---------------------------------------
    port = telemetry.start_http_server(0)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
        text = r.read().decode()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            hz = json.loads(r.read().decode())
    except urllib.error.HTTPError as e:   # degraded status is still JSON
        hz = json.loads(e.read().decode())
    if "bf_win_stale_rejected_total" not in text:
        failures.append("bf_win_stale_rejected_total missing on /metrics")
    ablock = hz.get("async")
    if not ablock:
        failures.append("no async block in /healthz")
    elif ablock.get("staleness_steps") != 4 or "stale_rejected" not in \
            ablock:
        failures.append(f"async /healthz block incomplete: {ablock}")

    # -- BLUEFOG_TPU_TELEMETRY=0 zero-mutation guard ------------------------
    os.environ["BLUEFOG_TPU_TELEMETRY"] = "0"
    _config.reload()
    telemetry.reset()
    W.clear_async_staleness()
    _, residual0, _, _ = drive([(40, stale)])
    leaked = telemetry.snapshot()
    if not residual0:
        failures.append("TELEMETRY=0 leg: policy did not apply (it is "
                        "state, not telemetry)")
    if leaked:
        failures.append("BLUEFOG_TPU_TELEMETRY=0 leg mutated the "
                        f"registry: {sorted(leaked)[:5]}")

    for var, val in prev.items():
        if val is None:
            os.environ.pop(var, None)
        else:
            os.environ[var] = val
    _config.reload()
    W.configure_async()
    W.clear_async_staleness()
    T.set_trace_origin_step(-1)
    telemetry.stop_http_server()

    rc = 0
    for f in failures:
        print(f"bench_comm --async-smoke: {f}", file=sys.stderr)
        rc = 1
    print(json.dumps({
        "metric": "win_async_stale_edges",
        "value": n_stale_edges,
        "unit": "edges",
        "detail": {
            "healthz_async": ablock,
            "fold_restored_exactly": rc == 0,
            "zero_mutation_ok": not leaked,
        },
    }))
    return rc


def _fused_env_setup():
    """Arm the whole-step rig's environment (idempotent; call BEFORE the
    first jax import): CPU backend, 8 virtual devices, native window
    transport + XLA put path.  Returns the saved env for restore."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8")
    prev = {v: os.environ.get(v) for v in (
        "BLUEFOG_TPU_WIN_NATIVE", "BLUEFOG_TPU_WIN_XLA",
        "BLUEFOG_TPU_WIN_COALESCE", "BLUEFOG_TPU_WIN_COALESCE_LINGER_MS",
        "BLUEFOG_TPU_WIN_COMPRESSION", "BLUEFOG_TPU_FUSED_STEP",
        "BLUEFOG_TPU_TELEMETRY")}
    os.environ.update({
        "BLUEFOG_TPU_WIN_NATIVE": "1",
        "BLUEFOG_TPU_WIN_XLA": "1",
        "BLUEFOG_TPU_WIN_COALESCE": "1",
        "BLUEFOG_TPU_WIN_COALESCE_LINGER_MS": "500",
        "BLUEFOG_TPU_WIN_COMPRESSION": "none",
        "BLUEFOG_TPU_TELEMETRY": "1",
    })
    os.environ.pop("BLUEFOG_TPU_FUSED_STEP", None)
    return prev


def _fused_env_restore(prev):
    from bluefog_tpu.ops import xlaffi
    from bluefog_tpu.utils import config as _config
    for var, val in prev.items():
        if val is None:
            os.environ.pop(var, None)
        else:
            os.environ[var] = val
    _config.reload()
    xlaffi._reset_for_tests()


def _fused_rig(fused, leaves, cols, buckets, steps, warm=0, synced=False,
               lr=0.5, armed=True):
    """One loopback leg of the whole-step rig: a ``leaves x (8, cols)``
    f32 tree stepped through a put-family optimizer against the
    two-transport loopback store pair (the test_win_xla rig — the
    windows predate the directory install, so one store serves both wire
    ends and every remote put really crosses TCP).

    ``synced=True`` gates each drain on every remote frame of the step
    having been applied (the loopback twin of a quiescent wire) — the
    determinism mode the trajectory-equivalence legs need; timing legs
    run ungated.  Returns (times_ms, final_params, fused_steps)."""
    import threading

    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    import bluefog_tpu as bf
    from bluefog_tpu import topology as topo
    from bluefog_tpu.ops import transport as T
    from bluefog_tpu.ops import window as W
    from bluefog_tpu.ops import xlaffi

    from bluefog_tpu.optim import window_optimizers as WO

    W.win_free()
    bf.init(lambda: topo.RingGraph(8))
    rs = np.random.RandomState(7)
    params = {f"l{i:02d}": jnp.asarray(rs.randn(8, cols)
                                       .astype(np.float32))
              for i in range(leaves)}
    opt = WO.DistributedWinPutOptimizer(optax.sgd(lr), fused=fused,
                                        fusion_buckets=buckets)
    st = opt.init(params)

    applied = [0]
    cv = threading.Condition()

    def bump(k):
        with cv:
            applied[0] += k
            cv.notify_all()

    def apply(op, name, src, dst, weight, p_weight, payload):
        W._apply_inbound(op, name, src, dst, weight, p_weight, payload)
        bump(1)

    def apply_batch(msgs):
        W._apply_inbound_batch(msgs)
        bump(len(msgs))

    def apply_items(items):
        W._apply_inbound_items(items)
        bump(sum((p[5] + p[6]) if k else 1 for k, p in items))

    server = T.WindowTransport(apply, apply_batch=apply_batch,
                               apply_items=apply_items)
    client = T.WindowTransport(lambda *a: None)
    saved = W._store.distrib
    orig_update = W.win_update
    expect = [0]

    def synced_update(name, **kw):
        with cv:
            assert cv.wait_for(lambda: applied[0] >= expect[0],
                               timeout=60), (applied[0], expect[0])
        return orig_update(name, **kw)

    try:
        assert client.native_path, "native transport sender required"
        for name, spl in zip(opt._names, opt._bucket_splits):
            server.register_window(name, int(spl[-1]))
        W._store.distrib = W._Distrib(
            client, rank_owner={r: r % 2 for r in range(8)},
            proc_addr={0: ("127.0.0.1", 1),
                       1: ("127.0.0.1", server.port)},
            my_proc=0)
        if armed:
            assert xlaffi.armed(), xlaffi.disarm_reason()
        if synced:
            W.win_update = synced_update
        p = params
        rng = np.random.RandomState(42)
        times = []
        n_windows = len(opt._names)
        for i in range(steps + warm):
            g = jax.tree.map(lambda x: x * 0.01 + jnp.asarray(
                rng.randn(*x.shape).astype(np.float32)) * 1e-3, p)
            # The bidirectional ring's out-edges from owned (even) srcs
            # all target odd dsts: 8 remote edges per op per window.
            expect[0] += 8 * n_windows
            t0 = time.perf_counter()
            p, st = opt.step(p, g, st, require_mutex=False)
            jax.block_until_ready(p)
            if i >= warm:
                times.append((time.perf_counter() - t0) * 1e3)
        fused_steps = (opt._fused_impl.fused_steps
                       if opt._fused_impl is not None else 0)
        return times, {k: np.asarray(v) for k, v in p.items()}, fused_steps
    finally:
        W.win_update = orig_update
        W._store.distrib = saved
        opt.free()
        client.stop()
        server.stop()


def _probe_overlap_cell(buckets: int, steps: int) -> Optional[dict]:
    """Measured-overlap summary over the fused leg's last ``steps``
    probe reconciles: the MEASURED ``bf_fused_overlap_ratio`` median
    (replacing the static model as the headline number), per-bucket
    p50/p99 put-issue latencies, and the modeled mean kept solely for
    the divergence ratio (the link-observatory x3 pattern)."""
    import numpy as np

    from bluefog_tpu.utils import probes
    rows = probes.recent_summaries(steps)
    if not rows:
        return None
    meas = [r["measured_overlap"] for r in rows]
    modeled = rows[-1].get("modeled_overlap")
    measured = float(np.median(meas))
    cell = {
        "measured_overlap": round(measured, 4),
        "modeled_overlap": modeled,
        "overlap_divergence": (round(measured / modeled, 3)
                               if modeled else None),
        "reconciled_steps": len(rows),
        "bucket_issue_us": {},
    }
    for bi in range(buckets):
        vals = [r["bucket_issue_seconds"][bi] * 1e6 for r in rows
                if bi in r["bucket_issue_seconds"]]
        if vals:
            cell["bucket_issue_us"][str(bi)] = {
                "p50": round(float(np.percentile(vals, 50)), 1),
                "p99": round(float(np.percentile(vals, 99)), 1),
            }
    return cell


def _fused_timing_cell(steps=40, warm=6):
    """The acceptance cell: eager vs fused end-to-end step time on the
    ungated loopback rig at the window-heavy configuration (32 leaves x
    (8, 128) over 8 fusion buckets = 8 in-program puts per step).

    When the native core carries the in-program probes the cell reports
    MEASURED overlap (median over the timed steps) with per-bucket
    p50/p99 issue latencies; the static model stays only as the
    denominator of the divergence ratio."""
    import numpy as np

    from bluefog_tpu.utils import probes, telemetry
    leaves, cols, buckets = 32, 128, 8
    te, _, _ = _fused_rig(False, leaves, cols, buckets, steps, warm)
    telemetry.reset()
    probes._reset_for_tests()
    tf, _, fsteps = _fused_rig(True, leaves, cols, buckets, steps, warm)
    snap = telemetry.snapshot()
    compile_s = snap.get("bf_fused_step_compile_seconds_sum", 0.0)
    e50, e99 = np.percentile(te, 50), np.percentile(te, 99)
    f50, f99 = np.percentile(tf, 50), np.percentile(tf, 99)
    cell = {
        "leaves": leaves, "cols": cols, "fusion_buckets": buckets,
        "steps": steps,
        "eager_ms_p50": round(float(e50), 3),
        "eager_ms_p99": round(float(e99), 3),
        "fused_ms_p50": round(float(f50), 3),
        "fused_ms_p99": round(float(f99), 3),
        "speedup": round(float(e50 / max(f50, 1e-9)), 3),
        "compile_seconds": round(float(compile_s), 3),
        "fused_steps": fsteps,
    }
    overlap = _probe_overlap_cell(buckets, steps)
    if overlap is not None:
        cell["overlap"] = overlap
    return cell


def _fused_report(smoke: bool):
    """The whole-step compilation gate: returns (speedup|None, detail,
    failures).  Callers set the env (``_fused_env_setup``) first.

    Structural legs (both modes):
      1. engagement — a gated loopback run where every step takes the
         fused path (``bf_fused_step_active`` 1, in-program puts
         counted), with the trajectory-equivalence assert (<= 1e-6 vs
         eager over the same gradient stream; bitwise expected — the
         2^-1 learning rate keeps the update multiply exact, so XLA's
         FMA contraction and eager's separate mul+add round the same);
      2. ``BLUEFOG_TPU_FUSED_STEP=0`` — bitwise identical to the eager
         leg AND inert (no program built, no bf_fused_step_* series);
      3. graceful fallback — with the XLA put path disarmed a
         ``fused=True`` optimizer warns ONCE, keeps stepping on the
         eager path, and reports inactive.
    Full runs add the timing cell and assert the >= 1.5x end-to-end
    step-time win."""
    import numpy as np

    from bluefog_tpu import native
    from bluefog_tpu.ops import xlaffi
    from bluefog_tpu.utils import config as _config
    from bluefog_tpu.utils import logging as bflog
    from bluefog_tpu.utils import telemetry
    _config.reload()
    xlaffi._reset_for_tests()

    if not (native.available() and native.has_win_xla()
            and native.has_xla_handler() and xlaffi.has_passthrough()):
        reason = ("native core lacks bf_xla_win_put_pass"
                  if native.available() else "native core unavailable")
        return None, {"skipped": reason}, []

    failures = []
    detail = {"smoke": smoke}
    steps = 10 if smoke else 50

    # -- leg 1: engagement + trajectory equivalence (gated loopback) --------
    telemetry.reset()
    _, pe, _ = _fused_rig(False, 2, 48, 2, steps, synced=True)
    _, pf, fsteps = _fused_rig(True, 2, 48, 2, steps, synced=True)
    snap = telemetry.snapshot()
    max_diff = max(float(np.abs(pe[k] - pf[k]).max()) for k in pe)
    bitwise = all(np.array_equal(pe[k], pf[k]) for k in pe)
    if fsteps != steps:
        failures.append(f"only {fsteps}/{steps} steps took the fused "
                        "path on the engagement leg")
    if snap.get("bf_fused_step_active") != 1.0:
        failures.append("bf_fused_step_active != 1 after a fused run")
    if not snap.get("bf_fused_step_puts_total"):
        failures.append("no in-program puts counted "
                        "(bf_fused_step_puts_total)")
    if max_diff > 1e-6:
        failures.append(f"fused-vs-eager trajectory diverged: max |d| = "
                        f"{max_diff} > 1e-6 over {steps} steps")
    detail["trajectory"] = {
        "steps": steps, "max_abs_diff": max_diff, "bitwise": bitwise,
        "puts_total": snap.get("bf_fused_step_puts_total", 0.0),
    }

    # -- leg 2: BLUEFOG_TPU_FUSED_STEP=0 is inert and bitwise eager ---------
    os.environ["BLUEFOG_TPU_FUSED_STEP"] = "0"
    _config.reload()
    telemetry.reset()
    try:
        _, p0, _ = _fused_rig(None, 2, 48, 2, steps, synced=True)
    finally:
        os.environ.pop("BLUEFOG_TPU_FUSED_STEP", None)
        _config.reload()
    snap0 = telemetry.snapshot()
    off_bitwise = all(np.array_equal(pe[k], p0[k]) for k in pe)
    if not off_bitwise:
        failures.append("FUSED_STEP=0 leg is not bitwise identical to "
                        "the eager oracle")
    leaked = [k for k in snap0 if k.startswith("bf_fused_step")]
    if leaked:
        failures.append(f"FUSED_STEP=0 leg registered {leaked[:3]}")
    detail["env_off"] = {"bitwise": off_bitwise, "inert": not leaked}

    # -- leg 3: graceful fallback when the XLA put path is disarmed ---------
    # Same loopback rig (distrib installed — the eligibility check only
    # applies to a live wire), XLA put path pinned off: the fused=True
    # optimizer must warn ONCE, keep stepping eager, report inactive,
    # and land the SAME trajectory.
    os.environ["BLUEFOG_TPU_WIN_XLA"] = "0"
    _config.reload()
    xlaffi._reset_for_tests()
    telemetry.reset()
    warns = []
    logger = bflog.get_logger()
    orig_warning = logger.warning
    logger.warning = lambda msg, *a, **kw: (
        warns.append(msg % a if a else msg), orig_warning(msg, *a, **kw))
    try:
        _, pfb, fb_steps = _fused_rig(True, 2, 48, 2, steps, synced=True,
                                      armed=False)
    finally:
        logger.warning = orig_warning
        os.environ["BLUEFOG_TPU_WIN_XLA"] = "1"
        _config.reload()
        xlaffi._reset_for_tests()
    fb_warns = [m for m in warns if "falling back to the eager path" in m]
    if len(fb_warns) != 1:
        failures.append(f"fallback leg warned {len(fb_warns)} times "
                        "(want exactly 1)")
    if fb_steps != 0:
        failures.append(f"fallback leg still took {fb_steps} fused steps "
                        "with the XLA put path disarmed")
    if telemetry.snapshot().get("bf_fused_step_active") != 0.0:
        failures.append("fallback leg did not report "
                        "bf_fused_step_active = 0")
    fb_bitwise = all(np.array_equal(pe[k], pfb[k]) for k in pe)
    if not fb_bitwise:
        failures.append("fallback leg's eager trajectory is not bitwise "
                        "identical to the eager oracle")
    detail["fallback"] = {"warnings": len(fb_warns),
                          "bitwise_eager": fb_bitwise}

    # -- timing cell (full runs only: shared CI boxes jitter) ---------------
    speedup = None
    if not smoke:
        cell = _fused_timing_cell()
        detail["timing"] = cell
        speedup = cell["speedup"]
        if speedup < 1.5:
            failures.append(f"fused end-to-end step speedup {speedup}x "
                            "< 1.5x on the transport rig")
    return speedup, detail, failures


def fused_main(args) -> int:
    """`make fused-smoke` / `--fused`: the whole-step compilation gate.

    Smoke: structural only — fused engagement (every step through the
    single XLA program, in-program puts counted), trajectory equivalence
    vs eager, FUSED_STEP=0 bitwise inertness, graceful one-warning
    fallback without the native XLA handler.  Full adds the eager-vs-
    fused timing cell and asserts the >= 1.5x end-to-end win."""
    import sys

    smoke = bool(args.fused_smoke and not args.fused)
    prev = _fused_env_setup()
    try:
        value, detail, failures = _fused_report(smoke)
    finally:
        _fused_env_restore(prev)
    rc = 0
    for f in failures:
        print(f"bench_comm --fused: {f}", file=sys.stderr)
        rc = 1
    print(json.dumps({
        "metric": "fused_step_speedup",
        "value": value,
        "unit": "x",
        "detail": detail,
    }))
    return rc


def probe_main(args) -> int:
    """`make probe-smoke`: the in-program probe CI gate.

    One fused loopback run (probes on by default) must land every probe
    surface: the measured ``bf_fused_overlap_ratio`` gauge in (0, 1],
    per-bucket ``bf_fused_bucket_issue_seconds`` histograms,
    ``bf_probe_events_total``, a finite measured-vs-modeled divergence
    ratio, and — with a timeline armed — trace-merge output that is
    valid JSON carrying the ``fused-probe`` lanes.  Structural only (no
    timing assertion); graceful skip when the native core predates
    ``bf_xla_probe``."""
    import sys
    import tempfile

    prev = _fused_env_setup()
    prev["BLUEFOG_TPU_PYTHON_TIMELINE"] = os.environ.get(
        "BLUEFOG_TPU_PYTHON_TIMELINE")
    # Probe lanes need the args-capable Python writer for lane naming,
    # and the in-band clock anchor keeps trace-merge alignment exact.
    os.environ["BLUEFOG_TPU_PYTHON_TIMELINE"] = "1"
    try:
        from bluefog_tpu import native, tools
        from bluefog_tpu.ops import xlaffi
        from bluefog_tpu.utils import config as _config
        from bluefog_tpu.utils import probes, telemetry, timeline
        _config.reload()
        xlaffi._reset_for_tests()
        if not (native.available() and native.has_win_xla()
                and native.has_xla_handler() and xlaffi.has_passthrough()
                and native.has_probe()):
            reason = ("native core lacks bf_xla_probe"
                      if native.available() else "native core unavailable")
            print(json.dumps({
                "metric": "probe_overlap_measured",
                "value": None, "unit": "ratio", "status": "no_probe",
                "detail": {"reason": reason}}))
            return 0

        failures = []
        buckets, steps = 2, 8
        tmpdir = tempfile.mkdtemp(prefix="bf-probe-smoke-")
        prefix = os.path.join(tmpdir, "tl_")
        telemetry.reset()
        probes._reset_for_tests()
        timeline.start_timeline(f"{prefix}0.json")
        try:
            _, _, fsteps = _fused_rig(True, 4, 64, buckets, steps)
        finally:
            timeline.stop_timeline()

        if fsteps != steps:
            failures.append(f"only {fsteps}/{steps} steps took the "
                            "fused path")
        snap = telemetry.snapshot()
        ratio = snap.get("bf_fused_overlap_ratio")
        if ratio is None or not (0.0 < ratio <= 1.0):
            failures.append(f"bf_fused_overlap_ratio {ratio!r} not in "
                            "(0, 1]")
        if not snap.get("bf_probe_events_total"):
            failures.append("bf_probe_events_total missing or zero")
        issue_counts = [k for k in snap
                        if k.startswith("bf_fused_bucket_issue_seconds"
                                        "_count")]
        if len(issue_counts) < buckets:
            failures.append("per-bucket issue histograms missing: "
                            f"{issue_counts}")
        div = snap.get("bf_fused_overlap_divergence_ratio")
        if div is None or not (div > 0):
            failures.append(f"divergence ratio {div!r} not finite/positive")

        summary = probes.last_summary()
        if summary is None:
            failures.append("probes.last_summary() is None after a "
                            "fused run")

        merged = tools.trace_merge(prefix)
        try:
            with open(merged) as f:
                events = json.load(f)  # must be VALID json
        except ValueError as e:
            events, failures = [], failures + [f"trace-merge output is "
                                               f"not valid JSON: {e}"]
        lanes = {e.get("tid") for e in events
                 if e.get("cat") == "fused-probe"}
        if not lanes:
            failures.append("no fused-probe lanes in the merged trace")

        rc = 0
        for f in failures:
            print(f"bench_comm --probe-smoke: {f}", file=sys.stderr)
            rc = 1
        print(json.dumps({
            "metric": "probe_overlap_measured",
            "value": ratio,
            "unit": "ratio",
            "detail": {
                "fused_steps": fsteps,
                "overlap": _probe_overlap_cell(buckets, steps),
                "probe_events": snap.get("bf_probe_events_total"),
                "divergence": div,
                "probe_lanes": sorted(int(t) for t in lanes
                                      if t is not None),
                "merged_events": len(events),
            },
        }))
        return rc
    finally:
        _fused_env_restore(prev)


def tracerec_main(args) -> int:
    """`make tracerec-smoke`: the message-level observability CI gate.

    Structural assertions, no timing:
      1. with the flight recorder armed and trace tags sampled at 1/2, a
         loopback window-store pair (real win_put/win_accumulate through
         the real drain path) lands `bf_win_contribution_age_seconds{src}`
         histograms + freshest/stalest gauges on /metrics and the
         contribution_age block in /healthz;
      2. the recorder ring carries the event chain (enqueue ... commit)
         and its dump decodes into a valid merged chrome trace with at
         least one matched flow arrow (trace-gossip);
      3. BLUEFOG_TPU_TELEMETRY=0 zero-mutation guard: the same traffic
         leaves the registry completely untouched (the recorder is an
         independent knob and may still record).
    """
    import sys
    import tempfile
    import threading
    import urllib.request

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8")
    prev = {v: os.environ.get(v) for v in (
        "BLUEFOG_TPU_TRACE_SAMPLE", "BLUEFOG_TPU_FLIGHT_RECORDER",
        "BLUEFOG_TPU_TELEMETRY", "BLUEFOG_TPU_WIN_COALESCE_LINGER_MS")}
    os.environ.update({
        "BLUEFOG_TPU_TRACE_SAMPLE": "2",
        "BLUEFOG_TPU_FLIGHT_RECORDER": "1",
        "BLUEFOG_TPU_TELEMETRY": "1",
        "BLUEFOG_TPU_WIN_COALESCE_LINGER_MS": "200",
    })
    import numpy as np

    import bluefog_tpu as bf
    from bluefog_tpu import native
    from bluefog_tpu import topology as topo
    from bluefog_tpu.ops import transport as T
    from bluefog_tpu.ops import window as W
    from bluefog_tpu.tools import tracegossip
    from bluefog_tpu.utils import config as _config
    from bluefog_tpu.utils import flightrec, telemetry
    _config.reload()
    if not native.available():
        print(json.dumps({
            "metric": "win_tracing_age_edges",
            "value": None, "unit": "edges", "status": "no_native",
            "detail": {"reason": "native core not built"}}))
        return 0
    failures = []
    bf.init(lambda: topo.RingGraph(8))
    telemetry.reset()

    def drive(n_steps=4):
        """A real put/accumulate stream through the loopback store (the
        window created pre-directory, so one store serves both wire
        ends — the test_win_xla pattern)."""
        applied = [0]
        cv = threading.Condition()

        def bump(k):
            with cv:
                applied[0] += k
                cv.notify_all()

        def apply(op, name, src, dst, weight, p_weight, payload):
            W._apply_inbound(op, name, src, dst, weight, p_weight, payload)
            bump(1)

        def apply_batch(msgs):
            W._apply_inbound_batch(msgs)
            bump(len(msgs))

        def apply_items(items):
            W._apply_inbound_items(items)
            bump(sum((p[5] + p[6]) if k else 1 for k, p in items))

        server = T.WindowTransport(apply, apply_batch=apply_batch,
                                   apply_items=apply_items)
        client = T.WindowTransport(lambda *a: None)
        saved = W._store.distrib
        rng = np.random.RandomState(7)
        try:
            assert bf.win_create(rng.randn(8, 6).astype(np.float32),
                                 "trc", zero_init=True)
            server.register_window("trc", 6)
            W._store.distrib = W._Distrib(
                client, rank_owner={r: r % 2 for r in range(8)},
                proc_addr={0: ("127.0.0.1", 1),
                           1: ("127.0.0.1", server.port)},
                my_proc=0)
            total = 0
            for step in range(n_steps):
                t = np.random.RandomState(100 + step) \
                    .randn(8, 6).astype(np.float32)
                if step % 2:
                    bf.win_accumulate(t, "trc")
                else:
                    bf.win_put(t, "trc")
                total += 8  # the ring's 8 remote (even->odd) edges per op
                with cv:
                    assert cv.wait_for(lambda: applied[0] >= total,
                                       timeout=30), (applied[0], total)
        finally:
            W._store.distrib = saved
            bf.win_free("trc")
            client.stop()
            server.stop()

    flightrec.reset()
    W.clear_contribution_age()
    drive()

    # -- leg 1: age telemetry on /metrics + /healthz ------------------------
    port = telemetry.start_http_server(0)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
        text = r.read().decode()
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
        hz = json.loads(r.read().decode())
    for series in ("bf_win_contribution_age_seconds_bucket",
                   "bf_win_contribution_freshest_age_seconds",
                   "bf_win_contribution_stalest_age_seconds"):
        if series not in text:
            failures.append(f"missing {series} on /metrics")
    ages = hz.get("contribution_age")
    if not ages:
        failures.append("no contribution_age block in /healthz")
    n_edges = len(ages or {})

    # -- leg 2: recorder chain + merged-trace decode ------------------------
    ev = flightrec.snapshot()
    etypes = set(int(e) for e in ev["etype"])
    want = {flightrec.ENQUEUE, flightrec.COMMIT}
    if not want <= etypes:
        failures.append(
            f"recorder event chain incomplete: have {sorted(etypes)}, "
            f"need at least {sorted(want)}")
    with tempfile.TemporaryDirectory() as tmp:
        prefix = os.path.join(tmp, "flightrec")
        path = flightrec.dump(path=f"{prefix}.0.bin", reason="smoke")
        if path is None:
            failures.append("flight recorder dump failed")
        else:
            out, stats = tracegossip.merge_gossip(prefix)
            with open(out) as f:
                json.load(f)  # valid chrome-trace JSON
            if stats["flows_matched"] < 1:
                failures.append(
                    f"no flow arrows matched in the merged trace "
                    f"({stats})")

    # -- leg 3: BLUEFOG_TPU_TELEMETRY=0 zero-mutation guard -----------------
    os.environ["BLUEFOG_TPU_TELEMETRY"] = "0"
    _config.reload()
    telemetry.reset()
    W.clear_contribution_age()
    drive(n_steps=2)
    leaked = telemetry.snapshot()
    if leaked:
        failures.append(
            "BLUEFOG_TPU_TELEMETRY=0 leg mutated the registry: "
            f"{sorted(leaked)[:5]}")

    for var, val in prev.items():
        if val is None:
            os.environ.pop(var, None)
        else:
            os.environ[var] = val
    _config.reload()
    telemetry.stop_http_server()

    rc = 0
    for f in failures:
        print(f"bench_comm --tracerec-smoke: {f}", file=sys.stderr)
        rc = 1
    print(json.dumps({
        "metric": "win_tracing_age_edges",
        "value": n_edges,
        "unit": "edges",
        "detail": {
            "contribution_age": ages,
            "rec_events": int(len(ev)),
            "etypes": sorted(etypes),
            "zero_mutation_ok": not leaked,
        },
    }))
    return rc


def _ffi_one_mode(mode: str, elems: int, bursts: int, per_burst: int):
    """Put-side microbench of one window put path through a loopback
    store: ``legacy`` (Python coalesced sender, WIN_NATIVE=0), ``native``
    (the PR-9 C++ sender fed by the host-staged put loop) and ``ffi``
    (the zero-copy XLA plan dispatch, WIN_XLA=1).

    Two numbers per mode:
      * ``dispatch_us_per_row`` — the put-side HOST overhead: min over
        bursts of the per-put dispatch wall time with the op-boundary
        flush factored OUT of the clock (queued frames ship once per
        burst outside it), so wire + drain time — identical across
        modes — cannot mask the host-path difference the tentpole
        targets;
      * ``msgs_per_s`` — end-to-end blocking-put throughput (clock stops
        at the last receiver apply), reported for context (no assertion:
        on a 2-core CI box it measures scheduler contention as much as
        the path).
    """
    import threading

    import numpy as np
    import jax.numpy as jnp

    import bluefog_tpu as bf
    from bluefog_tpu import topology as topo
    from bluefog_tpu.ops import transport as T
    from bluefog_tpu.ops import window as W
    from bluefog_tpu.ops import xlaffi
    from bluefog_tpu.utils import config, telemetry

    saved_env = {k: os.environ.get(k) for k in
                 ("BLUEFOG_TPU_WIN_COALESCE",
                  "BLUEFOG_TPU_WIN_COALESCE_LINGER_MS",
                  "BLUEFOG_TPU_WIN_NATIVE", "BLUEFOG_TPU_WIN_XLA",
                  "BLUEFOG_TPU_WIN_COMPRESSION")}
    os.environ.update(
        BLUEFOG_TPU_WIN_COALESCE="1",
        # Long linger: nothing ships inside the timed dispatch region;
        # the per-burst flush (outside the clock) puts it on the wire.
        BLUEFOG_TPU_WIN_COALESCE_LINGER_MS="2000",
        BLUEFOG_TPU_WIN_NATIVE="0" if mode == "legacy" else "1",
        BLUEFOG_TPU_WIN_XLA="1" if mode == "ffi" else "0",
        BLUEFOG_TPU_WIN_COMPRESSION="none")
    config.reload()
    xlaffi._reset_for_tests()
    telemetry.reset()
    bf.init(lambda: topo.RingGraph(8))
    applied = [0]
    cv = threading.Condition()

    def bump(k):
        with cv:
            applied[0] += k
            cv.notify_all()

    server = T.WindowTransport(
        lambda *a: bump(1),
        apply_batch=lambda m: bump(len(m)),
        apply_items=lambda it: bump(
            sum((p[5] + p[6]) if k else 1 for k, p in it)))
    client = T.WindowTransport(lambda *a: None)
    saved_distrib = W._store.distrib
    real_flush = W._flush_transport
    x = np.zeros((8, elems), np.float32)
    try:
        assert bf.win_create(x, "ffibench", zero_init=True)
        server.register_window("ffibench", elems)
        # Even ranks owned here; odd ranks' owner is the loopback server
        # feeding the same store — the ring's 8 even->odd out-edges all
        # travel the wire.
        W._store.distrib = W._Distrib(
            client, {r: r % 2 for r in range(8)},
            {0: ("127.0.0.1", 1), 1: ("127.0.0.1", server.port)}, 0)
        t = jnp.asarray(np.random.RandomState(0)
                        .randn(8, elems).astype(np.float32))
        t.block_until_ready()
        win = W._store.get("ffibench")
        edges = W._resolve_edge_weights(None, win.out_nbrs, 1.0,
                                        ranks=win.owned)
        W._do_put("ffibench", t, edges, False, False)  # warm plan/keys
        total_puts = 1
        times = []
        for _ in range(bursts):
            W._flush_transport = lambda *a, **k: None
            t0 = time.perf_counter()
            for _ in range(per_burst):
                W._do_put("ffibench", t, edges, False, False)
            times.append((time.perf_counter() - t0) / per_burst)
            W._flush_transport = real_flush
            W.win_flush()
            total_puts += per_burst
            with cv:
                assert cv.wait_for(
                    lambda: applied[0] >= total_puts * 8, timeout=120), \
                    (applied[0], total_puts * 8)
        # End-to-end throughput: blocking puts, clock to the last apply.
        e2e_puts = max(per_burst // 2, 20)
        before = applied[0]
        t0 = time.perf_counter()
        for _ in range(e2e_puts):
            bf.win_put(t, "ffibench", require_mutex=False)
        with cv:
            assert cv.wait_for(
                lambda: applied[0] >= before + e2e_puts * 8, timeout=120)
        e2e_dt = time.perf_counter() - t0
        snap = telemetry.snapshot()
        copies = {p: snap.get(
            f'bf_win_host_copy_bytes_total{{path="{p}"}}', 0)
            for p in ("device_get", "edge_temp", "enqueue")}
        return {
            "mode": mode,
            "row_bytes": elems * 4,
            "dispatch_us_per_put": round(min(times) * 1e6, 2),
            "dispatch_us_per_row": round(min(times) * 1e6 / 8, 3),
            "msgs_per_s": round(e2e_puts * 8 / e2e_dt, 1),
            "ffi_engaged": snap.get("bf_win_xla_puts_total", 0) > 0,
            "host_copy_bytes": copies,
        }
    finally:
        W._flush_transport = real_flush
        W._store.distrib = saved_distrib
        bf.win_free("ffibench")
        client.stop()
        server.stop()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        config.reload()
        xlaffi._reset_for_tests()


def ffi_main(args) -> int:
    """The zero-copy XLA put-path report (and the `make ffi-smoke` CI
    gate).  Graceful skip — not a failure — when jax.ffi or the native
    ``bf_xla`` symbols are absent: that is the documented degraded mode
    (the host-staged PR-9 path serves every put)."""
    import sys

    smoke = args.ffi_smoke
    # The loopback store runs on the CPU backend's virtual mesh; size it
    # BEFORE jax initializes (same rule as the schedule bench).
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8")

    from bluefog_tpu import _compat, native

    if not (native.available() and native.has_win_xla()
            and _compat.jax_ffi() is not None):
        reason = ("native core lacks bf_xla symbols"
                  if native.available() else "native core not built")
        if _compat.jax_ffi() is None:
            reason = "jax has no ffi module"
        print(json.dumps({
            "metric": "win_put_ffi_dispatch_speedup", "value": None,
            "unit": "x", "status": "skipped",
            "detail": {"reason": reason}}))
        return 0
    from bluefog_tpu.ops import xlaffi
    if not xlaffi.armed():
        print(json.dumps({
            "metric": "win_put_ffi_dispatch_speedup", "value": None,
            "unit": "x", "status": "skipped",
            "detail": {"reason": xlaffi.disarm_reason()}}))
        return 0

    value, detail, failures = _ffi_report(smoke)
    rc = 0
    for f in failures:
        print(f"bench_comm --ffi: {f}", file=sys.stderr)
        rc = 1
    print(json.dumps({
        "metric": "win_put_ffi_dispatch_speedup",
        "value": value,
        "unit": "x",
        "detail": detail,
    }))
    return rc


def _ffi_report(smoke: bool):
    """Run the FFI put-path sweep; returns ``(speedup, detail,
    failures)``.  Shared by ``--ffi[-smoke]`` and the full
    ``--transport`` run's ffi leg."""
    bursts, per_burst = (3, 30) if smoke else (10, 100)
    sizes = [1024] if smoke else [256, 1024, 16384]  # f32 elems per row
    sweep, failures = [], []
    for elems in sizes:
        for mode in (["native", "ffi"] if smoke
                     else ["legacy", "native", "ffi"]):
            res = _ffi_one_mode(mode, elems, bursts, per_burst)
            sweep.append(res)
            if mode == "ffi":
                if not res["ffi_engaged"]:
                    failures.append(
                        f"FFI path armed but did not engage ({elems} elems)")
                bad = {p: b for p, b in res["host_copy_bytes"].items()
                       if b > 0}
                if bad:
                    failures.append(
                        f"FFI leg reported staging copies {bad} "
                        f"({elems} elems) — the zero-copy contract broke")

    def _us(mode, elems):
        for r in sweep:
            if r["mode"] == mode and r["row_bytes"] == elems * 4:
                return r["dispatch_us_per_row"]
        return None

    ratios = {}
    for elems in sizes:
        nat, ffi = _us("native", elems), _us("ffi", elems)
        if nat and ffi:
            ratios[elems * 4] = round(nat / ffi, 2)
    big_ratio = min((v for k, v in ratios.items() if k >= 4096),
                    default=None)
    if not smoke and (big_ratio is None or big_ratio < 2.0):
        failures.append(
            f"FFI put dispatch speedup {big_ratio}x < 2x vs the PR-9 "
            "native path for rows >= 4 KiB")
    detail = {"smoke": smoke, "ratios_by_row_bytes": ratios,
              "sweep": sweep}
    return big_ratio, detail, failures


def _effective_w(sched, n):
    """Reconstruct the effective weight matrix a compiled schedule applies
    (the repack-equivalence oracle: regrouping rounds must never change it)."""
    import numpy as np
    w = np.zeros((n, n))
    w[np.arange(n), np.arange(n)] = sched.self_scale
    for rnd in sched.rounds:
        for s, d in rnd.pairs:
            w[s, d] = rnd.send_scale[s]
    return w


def placement_main(args) -> int:
    """Physical-placement report (and the `make placement-smoke` CI gate).

    Part 1 is pure host math (no jax): for each simulated torus and each
    topology family, compare modeled max-link-load under identity
    placement vs the optimized permutation vs optimized + congestion-aware
    round packing; assert random-regular improves >= 2x on the 8x8 torus,
    shift-structured families are never made worse, and the effective
    weight matrix survives the repack bit-identically.  Part 2 drives the
    real op on the virtual 8-device CPU mesh: placement on (fake torus)
    must produce BIT-IDENTICAL outputs vs BLUEFOG_TPU_PLACEMENT=0 (the
    permutation only moves ranks to other devices), and the congestion
    repack stays within 1e-6 (fp summation order only)."""
    import numpy as np

    from bluefog_tpu import topology as topo
    from bluefog_tpu.ops import placement as PL
    from bluefog_tpu.ops import schedule as S
    from bluefog_tpu.ops import schedule_opt as SO

    smoke = args.placement_smoke
    seed = args.seed
    tori = {}
    for dims in ((4, 8), (8, 8)):
        n = dims[0] * dims[1]
        model = PL.synthetic_torus(dims)
        per_topo = {}
        for name, make in _topo_families(topo, n, seed):
            w = topo.weight_matrix(make())
            sched = S._build_schedule(w, optimize=True)
            res = PL.optimize_placement(model, sched, n,
                                        iters=args.placement_iters,
                                        seed=seed)
            packed = SO.congestion_aware_repack(
                sched, model, res.perm, budget_factor=2.0)
            pc = PL.schedule_cost(model, packed, res.perm)
            assert np.array_equal(_effective_w(sched, n),
                                  _effective_w(packed, n)), \
                f"{name}@{dims}: repack changed the effective weight matrix"
            assert (res.optimized_cost.max_link_load
                    <= res.identity_cost.max_link_load), \
                f"{name}@{dims}: placement made max-link-load WORSE"
            assert pc.max_link_load <= res.optimized_cost.max_link_load, \
                f"{name}@{dims}: congestion repack made max-link-load WORSE"
            per_topo[name] = {
                "max_link_load_naive": res.identity_cost.max_link_load,
                "max_link_load_placed": res.optimized_cost.max_link_load,
                "max_link_load_packed": pc.max_link_load,
                "hop_bytes_naive": res.identity_cost.hop_bytes,
                "hop_bytes_opt": res.optimized_cost.hop_bytes,
                "rounds": len(sched.rounds),
                "rounds_packed": len(packed.rounds),
                "identity_placement": res.is_identity,
                "improvement_ratio": round(
                    res.identity_cost.max_link_load
                    / max(pc.max_link_load, 1e-12), 3),
            }
        tori["x".join(map(str, dims))] = per_topo

    rr = tori["8x8"]["random_regular"]
    assert rr["improvement_ratio"] >= 2.0, (
        "placement+packing must cut modeled max-link-load >= 2x for "
        f"random-regular(4, 64) on the 8x8 torus, got "
        f"{rr['improvement_ratio']}x")

    # ---- Part 2: end-to-end output equivalence on the virtual CPU mesh.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8")
    import jax
    jax.config.update("jax_platforms", "cpu")

    import bluefog_tpu as bf
    from bluefog_tpu.utils import config

    topo_fn = lambda: topo.RandomRegularGraph(8, 4, seed=1)
    x = np.random.default_rng(seed).standard_normal((8, 64)).astype(
        np.float32)
    knobs = ("BLUEFOG_TPU_PLACEMENT", "BLUEFOG_TPU_FAKE_TORUS",
             "BLUEFOG_TPU_PLACEMENT_ROUND_BUDGET")
    saved = {k: os.environ.get(k) for k in knobs}

    def run(**env):
        for k in knobs:
            os.environ.pop(k, None)
        os.environ.update(env)
        config.reload()
        bf.init(topo_fn)
        out = np.asarray(bf.neighbor_allreduce(x))
        info = bf.placement_info()
        bf.shutdown()
        return out, info

    try:
        out_off, info_off = run(BLUEFOG_TPU_PLACEMENT="0",
                                BLUEFOG_TPU_FAKE_TORUS="2x4")
        out_place, info_on = run(BLUEFOG_TPU_PLACEMENT="1",
                                 BLUEFOG_TPU_FAKE_TORUS="2x4",
                                 BLUEFOG_TPU_PLACEMENT_ROUND_BUDGET="0")
        out_pack, _ = run(BLUEFOG_TPU_PLACEMENT="1",
                          BLUEFOG_TPU_FAKE_TORUS="2x4")
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        config.reload()
    assert info_off is None, "PLACEMENT=0 must disable the physical model"
    assert info_on is not None and (info_on["max_link_load_opt"]
                                    <= info_on["max_link_load_naive"])
    assert np.array_equal(out_off, out_place), (
        "placement permutation must be BIT-identical to enumeration order "
        "(it only moves ranks to other devices)")
    pack_diff = float(np.abs(out_off - out_pack).max())
    assert pack_diff <= 1e-6, \
        f"congestion repack drifted outputs by {pack_diff} (> 1e-6)"

    print(json.dumps({
        "metric": "gossip_placement_max_link_load_reduction_random_regular",
        "value": rr["improvement_ratio"],
        "unit": "x",
        "detail": {
            "smoke": smoke,
            "tori": tori,
            "e2e": {
                "mesh": "8-device CPU, fake torus 2x4",
                "bit_identical_placement_only": True,
                "packed_max_output_diff": pack_diff,
                "placement_info": info_on,
            },
        },
    }))
    return 0


def _dcn_serial_time(model, sched) -> float:
    """Modeled inter-slice serial link time of one application of
    ``sched``: sum over rounds of the busiest DCN link's weighted load —
    the ICI portion deliberately excluded (the DCN links are the scarce
    pod-scale resource this report isolates)."""
    import numpy as np
    node = np.asarray(model.device_node, np.int64)
    first_dcn = model.first_dcn_link
    total = 0.0
    for rnd in sched.rounds:
        loads = np.zeros(model.n_links)
        for s, d in rnd.pairs:
            r = model.route(int(node[s]), int(node[d]))
            np.add.at(loads, r, 1.0)
        dcn = loads[first_dcn:] * model.dcn_link_cost
        if dcn.size:
            total += float(dcn.max())
    return total


def _dcn_rows(w, n_slices) -> int:
    """Directed inter-slice edges of one application of a flat weight
    matrix over slice-contiguous rank blocks."""
    import numpy as np
    n = w.shape[0]
    slice_of = np.arange(n) // (n // n_slices)
    srcs, dsts = np.nonzero(w)
    return int(sum(1 for s, d in zip(srcs, dsts)
                   if s != d and slice_of[s] != slice_of[d]))


def _simulate_hier_consensus(ht, w_flat, steps, frac, seed, dim=8):
    """Consensus distance (mean per-rank L2 to the global mean) of flat
    gossip vs the two-level mode after ``steps`` applications, simulated
    exactly on the per-step effective operators (the sparse outer level
    applies the block-restricted exchange per coordinate, matching the
    compiled executor)."""
    import math

    import numpy as np
    n = ht.n
    rng = np.random.default_rng(seed)
    x0 = rng.standard_normal((n, dim))
    kk = max(1, int(math.ceil(frac * dim)))
    nblocks = max(1, -(-dim // kk))
    w_in_full = ht.inner_full_matrix()

    def dist(x):
        return float(np.linalg.norm(x - x.mean(axis=0, keepdims=True),
                                    axis=1).mean())

    xf = x0.copy()
    xh = x0.copy()
    for step in range(steps):
        xf = w_flat.T @ xf
        xh = w_in_full.T @ xh
        if ht.is_outer_step(step):
            outer_step = step // ht.outer_every
            rot = (np.arange(kk) + (outer_step % nblocks) * kk) % dim
            p = ht.outer_phase_index(step, sweep_len=nblocks)
            wo = ht.outer_full_matrix(p)
            xh[:, rot] = wo.T @ xh[:, rot]
    return dist(xf), dist(xh)


def hier_main(args) -> int:
    """Hierarchical-gossip report (and the `make hier-smoke` CI gate).

    Part 1 is pure host math: on simulated multi-slice tori (2 slices of
    4x8, 4 slices of 4x4 — 64 ranks each) compare flat static Exp2
    against the two-level mode (dense inner exp2 over ICI, one-peer exp2
    outer over DCN at cadence 2 with sparse:0.5 outer compression and the
    cadence-corrected self weight sqrt(1/2) -> 1/2 per exchange — exact
    pairwise averaging, so a full outer phase sweep annihilates every
    inter-slice mode).  Asserts, per torus: per-step DCN wire rows AND
    modeled inter-slice serial link time both drop >= 4x, at
    equal-or-better simulated consensus distance after a fixed step
    budget.

    Part 2 drives the real executor on the 8-device virtual CPU mesh:
    dense/uncompressed/cadence-1 hierarchical_gossip must match flat
    neighbor_allreduce over the product topology <= 1e-6, the
    BLUEFOG_TPU_HIER=0 flat path must be BIT-identical to the unset-knob
    tree, and the sparse:<frac> wire codec must round-trip bit-exact
    through the OP_BATCH framing."""
    import math

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8")

    import numpy as np

    from bluefog_tpu import topology as topo
    from bluefog_tpu.ops import placement as PL
    from bluefog_tpu.ops import schedule as S

    smoke = args.hier_smoke
    outer_every = 2
    frac = 0.5
    # Cadence-corrected: theta**outer_every == 0.5 per exchange — exact
    # pairwise averaging, the weight under which a full one-peer exp2
    # sweep is an exact inter-slice average.
    theta = math.sqrt(0.5)
    budget_steps = 24
    tori = {
        "2x(4x8)": ((4, 8), 2),
        "4x(4x4)": ((4, 4), 4),
    }
    detail = {}
    worst_bytes_ratio = None
    for tname, (dims, n_slices) in tori.items():
        model = PL.synthetic_torus(dims, n_slices=n_slices)
        n = len(model.device_node)
        ht = topo.hierarchical_two_level(
            n, n_slices, outer_every=outer_every, outer_self_weight=theta)
        w_flat = topo.weight_matrix(topo.ExponentialTwoGraph(n))
        flat_sched = S._build_schedule(w_flat, optimize=True)

        # -- per-step DCN wire rows (row-bytes at unit payload) ------------
        flat_rows = _dcn_rows(w_flat, n_slices)
        hier_rows = ht.dcn_edges_per_outer_step() * frac / outer_every
        bytes_ratio = flat_rows / max(hier_rows, 1e-12)

        # -- modeled inter-slice serial link time per step -----------------
        flat_dcn_serial = _dcn_serial_time(model, flat_sched)
        outer_scheds = [
            S._build_schedule(ht.outer_full_matrix(p), optimize=True)
            for p in range(len(ht.outer_phases))]
        hier_dcn_serial = (sum(_dcn_serial_time(model, s)
                               for s in outer_scheds)
                           / max(len(outer_scheds), 1)
                           * frac / outer_every)
        serial_ratio = flat_dcn_serial / max(hier_dcn_serial, 1e-12)

        # -- consensus distance after the fixed step budget ----------------
        flat_dist, hier_dist = _simulate_hier_consensus(
            ht, w_flat, budget_steps, frac, args.seed)

        assert bytes_ratio >= 4.0, (
            f"{tname}: hierarchical DCN wire rows must drop >= 4x vs "
            f"flat exp2, got {bytes_ratio:.2f}x")
        assert serial_ratio >= 4.0, (
            f"{tname}: modeled inter-slice serial time must drop >= 4x, "
            f"got {serial_ratio:.2f}x")
        assert hier_dist <= flat_dist + 1e-12, (
            f"{tname}: hierarchical consensus distance {hier_dist:.3e} "
            f"worse than flat {flat_dist:.3e} after {budget_steps} steps")
        worst_bytes_ratio = (bytes_ratio if worst_bytes_ratio is None
                             else min(worst_bytes_ratio, bytes_ratio))
        detail[tname] = {
            "n": n, "n_slices": n_slices,
            "dcn_rows_flat_per_step": flat_rows,
            "dcn_rows_hier_per_step": hier_rows,
            "dcn_rows_reduction": round(bytes_ratio, 3),
            "dcn_serial_flat": flat_dcn_serial,
            "dcn_serial_hier": round(hier_dcn_serial, 4),
            "dcn_serial_reduction": round(serial_ratio, 3),
            "consensus_flat": flat_dist,
            "consensus_hier": hier_dist,
            "steps": budget_steps,
            "policy": {"inner": "exp2", "outer": "exp2 one-peer",
                       "outer_every": outer_every,
                       "outer_compression": f"sparse:{frac}",
                       "outer_self_weight_per_exchange": 0.5},
        }

    # ---- Part 2a: sparse wire codec through the OP_BATCH framing --------
    from bluefog_tpu.ops import transport as T
    rng = np.random.default_rng(args.seed)
    row = rng.standard_normal(64).astype(np.float32)
    idx = np.argsort(-np.abs(row))[:16].astype(np.int32)
    idx.sort()
    payload = T.sparse_encode(row[idx], idx)
    msgs = [(T.OP_ACCUMULATE | T.OP_SPARSE_FLAG, "w", 0, 1, 1.0, 0.0,
             payload.tobytes())]
    decoded = T._decode_batch(T._encode_batch(msgs))
    d_idx, d_val = T.sparse_decode(decoded[0][6])
    assert np.array_equal(d_idx, idx) and np.array_equal(
        d_val.view(np.int32), row[idx].view(np.int32)), \
        "sparse payload must round-trip BIT-exact through OP_BATCH framing"

    # ---- Part 2b: end-to-end executor equivalence on the CPU mesh -------
    import jax
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import bluefog_tpu as bf
    from bluefog_tpu.utils import config
    knobs = ("BLUEFOG_TPU_HIER", "BLUEFOG_TPU_HIER_OUTER_EVERY",
             "BLUEFOG_TPU_HIER_OUTER_COMPRESSION")
    saved = {k: os.environ.get(k) for k in knobs}
    x8 = np.random.default_rng(args.seed).standard_normal(
        (8, 16)).astype(np.float32)
    e2e = {}
    try:
        for k in knobs:
            os.environ.pop(k, None)
        config.reload()
        bf.init(lambda: topo.ExponentialGraph(8), local_size=4)
        out_unset = np.asarray(bf.neighbor_allreduce(x8))
        bf.shutdown()

        os.environ["BLUEFOG_TPU_HIER"] = "1"
        config.reload()
        bf.init(lambda: topo.ExponentialGraph(8), local_size=4)
        out_flat = np.asarray(bf.neighbor_allreduce(x8))
        assert np.array_equal(out_unset, out_flat), (
            "flat neighbor_allreduce must be BIT-identical with "
            "BLUEFOG_TPU_HIER on vs unset (the knob gates only the "
            "hierarchical path)")
        ht8 = topo.hierarchical_two_level(8, 2)
        max_diff = 0.0
        for step in range(4):
            out_h = np.asarray(bf.hierarchical_gossip(x8, step))
            expect = np.asarray(bf.neighbor_allreduce(
                x8, src_weights=ht8.effective_weight_matrix(step)))
            max_diff = max(max_diff,
                           float(np.abs(out_h - expect).max()))
        assert max_diff <= 1e-6, (
            f"dense cadence-1 hierarchical gossip drifted {max_diff} "
            "(> 1e-6) from the flat product topology")
        e2e = {"mesh": "8-device CPU, 2 slices of 4",
               "product_equivalence_max_diff": max_diff,
               "hier_info": bf.hierarchical_gossip_info()}
        bf.shutdown()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        config.reload()

    print(json.dumps({
        "metric": "hier_gossip_dcn_wire_reduction_worst_torus",
        "value": round(worst_bytes_ratio, 3),
        "unit": "x",
        "detail": {"smoke": smoke, "tori": detail, "e2e": e2e},
    }))
    return 0


def _topo_families(topo, n, seed, degree=4):
    """The four benchmark topology families every report sweeps."""
    return (
        ("ring", lambda: topo.RingGraph(n)),
        ("exp2", lambda: topo.ExponentialTwoGraph(n)),
        ("star", lambda: topo.StarGraph(n)),
        ("random_regular",
         lambda: topo.RandomRegularGraph(n, degree, seed=seed)),
    )


def synth_main(args) -> int:
    """Schedule-synthesis report (and the `make synth-smoke` CI gate).

    Part 1 is pure host math: for each simulated torus (4x8, 8x8, a
    2-slice 4x8 and a 4-slice 4x4) and each topology family, compare
    modeled serial_link_time of the König schedule, the congestion-aware
    repack, and the sketch-synthesis selection, all under identity
    placement (isolating the round-assignment axis).  Asserts the
    selection NEVER loses to the packed schedule, beats it strictly on
    the acceptance cases (exp2 + random-regular on the tori with
    headroom), and — where it ties on exp2/random-regular — that the
    packed schedule already sits on the provable busiest-link-total lower
    bound, i.e. no schedule could do better.  Effective weight matrices
    must survive synthesis bit-identically and round budgets must hold.

    Part 2 drives a genuinely synthesized schedule end-to-end through the
    real ppermute executor on a 32-device virtual CPU mesh and asserts
    output equivalence <= 1e-6 vs the naive schedule, then checks the
    `BLUEFOG_TPU_SCHEDULE_SYNTH=0` hatch restores the PR-5 dispatch path
    (no synthesis info, no synthesis gauges) with equivalent outputs."""
    import math as _math

    # The e2e leg needs a >= 32-device virtual mesh: size it BEFORE any
    # jax import (same contract as the schedule bench below).
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=32")

    import numpy as np

    from bluefog_tpu import topology as topo
    from bluefog_tpu.ops import placement as PL
    from bluefog_tpu.ops import schedule as S
    from bluefog_tpu.ops import schedule_opt as SO
    from bluefog_tpu.ops import synthesis as SY

    smoke = args.synth_smoke
    budget = 2.0
    tori = {
        "4x8": PL.synthetic_torus((4, 8)),
        "8x8": PL.synthetic_torus((8, 8)),
        "2x(4x8)": PL.synthetic_torus((4, 8), n_slices=2),
        "4x(4x4)": PL.synthetic_torus((4, 4), n_slices=4),
    }
    # The acceptance cases: exp2 + random-regular(4) must win strictly
    # wherever the packed schedule is NOT already at the lower bound.
    detail = {}
    strict_wins = []
    for tname, model in tori.items():
        n = len(model.device_node)
        per_topo = {}
        for name, make in _topo_families(topo, n, args.seed):
            w = topo.weight_matrix(make())
            naive = S._build_schedule(w, optimize=False)
            konig = SO.optimize_schedule(naive)
            packed = SO.congestion_aware_repack(
                konig, model, None, budget_factor=budget, record=False)
            chosen, ratio = SY.select_schedule(konig, packed, model, None,
                                               budget_factor=budget)
            ks = PL.schedule_cost(model, konig).serial_link_time
            ps = PL.schedule_cost(model, packed).serial_link_time
            cs = PL.schedule_cost(model, chosen).serial_link_time
            lb = SY.serial_lower_bound(model, konig)
            assert cs <= ps + 1e-9, \
                f"{name}@{tname}: synthesis selection made serial WORSE"
            assert np.array_equal(_effective_w(naive, n),
                                  _effective_w(chosen, n)), \
                f"{name}@{tname}: synthesis changed the weight matrix"
            assert len(chosen.rounds) <= max(
                len(konig.rounds),
                _math.ceil(budget * SO.min_rounds(konig))), \
                f"{name}@{tname}: synthesis exceeded the round budget"
            if name in ("exp2", "random_regular"):
                if cs < ps - 1e-9:
                    strict_wins.append(f"{name}@{tname}")
                else:
                    # No win allowed ONLY at provable optimality.
                    assert ps <= lb + 1e-9, (
                        f"{name}@{tname}: synthesis tied the packed "
                        f"schedule at {ps} > lower bound {lb} — headroom "
                        "left on the table")
            per_topo[name] = {
                "serial_konig": ks, "serial_packed": ps,
                "serial_synth": cs, "lower_bound": lb,
                "rounds_synth": len(chosen.rounds),
                "provenance": S.schedule_provenance(chosen),
                "improvement_ratio": round(ps / max(cs, 1e-12), 3),
            }
        detail[tname] = per_topo
    for required in ("exp2@8x8", "random_regular@8x8",
                     "random_regular@4x(4x4)"):
        assert required in strict_wins, (
            f"synthesis must beat congestion_aware_repack strictly on "
            f"{required}; wins: {strict_wins}")

    # ---- Part 2a: synthesized schedule through the real ppermute path.
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    devs = jax.devices()
    e2e = {}
    if len(devs) >= 32:
        n = 32
        mesh = Mesh(np.asarray(devs[:n]), ("r",))
        from bluefog_tpu.ops import collective as C
        model = PL.synthetic_torus((4, 8))
        w = topo.weight_matrix(topo.ExponentialTwoGraph(n))
        naive = S._build_schedule(w, optimize=False)
        konig = SO.optimize_schedule(naive)
        packed = SO.congestion_aware_repack(konig, model, None,
                                            budget_factor=budget,
                                            record=False)
        chosen, ratio = SY.select_schedule(konig, packed, model, None,
                                           budget_factor=budget)
        assert S.schedule_provenance(chosen).startswith("synthesized"), \
            "e2e leg expected a synthesized win for exp2(32) on 4x8"
        x = jnp.asarray(np.random.default_rng(args.seed)
                        .standard_normal((n, 256)), jnp.float32)

        def run(sched):
            return np.asarray(jax.jit(jax.shard_map(
                lambda b: C.neighbor_allreduce(b[0], sched, "r")[None],
                mesh=mesh, in_specs=P("r"), out_specs=P("r"),
                check_vma=False))(x))
        diff = float(np.abs(run(naive) - run(chosen)).max())
        assert diff <= 1e-6, \
            f"synthesized schedule drifted outputs by {diff} (> 1e-6)"
        e2e["synth_vs_naive_max_diff"] = diff
        e2e["synth_provenance"] = S.schedule_provenance(chosen)
        e2e["synth_serial"] = PL.schedule_cost(model, chosen).serial_link_time
        e2e["packed_serial"] = PL.schedule_cost(model, packed).serial_link_time

    # ---- Part 2b: the env hatch restores the PR-5 dispatch path.
    import bluefog_tpu as bf
    from bluefog_tpu.utils import config, telemetry
    knobs = ("BLUEFOG_TPU_SCHEDULE_SYNTH", "BLUEFOG_TPU_FAKE_TORUS",
             "BLUEFOG_TPU_PLACEMENT")
    saved = {k: os.environ.get(k) for k in knobs}
    topo_fn = lambda: topo.RandomRegularGraph(8, 4, seed=1)
    x8 = np.random.default_rng(args.seed).standard_normal(
        (8, 64)).astype(np.float32)

    def run_ctx(**env):
        for k in knobs:
            os.environ.pop(k, None)
        os.environ.update(env)
        config.reload()
        bf.init(topo_fn, devices=jax.devices()[:8])
        out = np.asarray(bf.neighbor_allreduce(x8))
        info = bf.synthesis_info()
        snap = telemetry.snapshot() if telemetry.enabled() else {}
        bf.shutdown()
        return out, info, snap

    try:
        out_off, info_off, snap_off = run_ctx(
            BLUEFOG_TPU_SCHEDULE_SYNTH="0", BLUEFOG_TPU_FAKE_TORUS="2x4")
        out_on, info_on, snap_on = run_ctx(
            BLUEFOG_TPU_SCHEDULE_SYNTH="1", BLUEFOG_TPU_FAKE_TORUS="2x4")
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        config.reload()
    assert info_off is None, \
        "SCHEDULE_SYNTH=0 must disable the synthesis pipeline entirely"
    assert "bf_schedule_synth_improvement_ratio" not in snap_off
    assert info_on is not None and info_on["improvement_ratio"] >= 1.0
    assert snap_on.get("bf_schedule_synth_improvement_ratio", 0) >= 1.0
    hatch_diff = float(np.abs(out_off - out_on).max())
    assert hatch_diff <= 1e-6, \
        f"env hatch outputs drifted by {hatch_diff} (> 1e-6)"
    e2e["hatch_max_diff"] = hatch_diff

    rr = detail["8x8"]["random_regular"]
    print(json.dumps({
        "metric": "gossip_schedule_synth_serial_time_reduction_rr_8x8",
        "value": rr["improvement_ratio"],
        "unit": "x",
        "detail": {
            "smoke": smoke,
            "strict_wins": strict_wins,
            "tori": detail,
            "e2e": e2e,
        },
    }))
    return 0


def sharded_main(args) -> int:
    """Sharded-gossip report (and the `make sharded-smoke` CI gate).

    Part 1 is pure host math: on a simulated 16-rank MoE mesh (4 replica
    groups of 4 — i.e. 4-way expert sharding) build trees whose
    replicated byte fraction is 25/50/75% and assert, through the
    ``ShardPlan`` planner and the per-group compiled schedules, that
    per-step DCN bytes scale with the replicated fraction ONLY: the
    sharded slices ride in-group edges exclusively, so a 50%-sharded
    tree gossips <= ~50% of the all-replicated path's DCN bytes.

    Part 2 drives the real executor on the 8-device virtual CPU mesh
    (2 replica groups of 4): the replicated leaf must match the dense
    ``W^T x`` oracle <= 1e-6, each rank's own shard slice must match the
    per-group oracle with its ghost region bit-untouched, the
    ``bf_comm_level_bytes_total{shard=...}`` split must bill exactly
    rep_row_bytes x dcn_edges x steps to the DCN (and never a sharded
    byte), and BLUEFOG_TPU_SHARDED_GOSSIP=0 — or a fully replicated
    tree — must be BIT-identical to the no-spec path."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8")

    import numpy as np

    from bluefog_tpu import topology as topo
    from bluefog_tpu.ops import schedule as S
    from bluefog_tpu.ops import sharded as SH

    smoke = args.sharded_smoke

    # ---- Part 1: planner byte model on a simulated 16-rank MoE mesh -----
    n, n_shards = 16, 4
    groups = SH.default_groups(n, n_shards)
    sched = S.compile_static(topo.ExponentialTwoGraph(n))
    total_cols = 4096  # floats per rank across the whole tree
    detail = {}
    baseline_dcn = None  # all-replicated DCN bytes per step
    for frac in (1.0, 0.75, 0.5, 0.25):
        rep_cols = int(total_cols * frac)
        sh_cols = (total_cols - rep_cols) // n_shards
        tree = {"router": np.zeros((n, rep_cols), np.float32)}
        specs = {"router": None}
        if sh_cols:
            tree["experts"] = np.zeros((n, n_shards, sh_cols), np.float32)
            specs["experts"] = ("ep", None)
        plan = SH.build_plan(tree, specs, n=n, n_shards=n_shards,
                             groups=groups)
        assert abs(plan.replicated_fraction - frac) < 1e-9, (
            frac, plan.replicated_fraction)
        rep_ici, rep_dcn = SH.edge_level_counts(plan.coords, sched)
        rep_row = plan.rep_bytes / n
        sh_row = plan.sh_bytes / n / n_shards if plan.any_sharded else 0.0
        dcn_bytes = rep_row * rep_dcn  # sharded slices: in-group only
        gsched, per_group = SH.compile_group_schedules(n, groups)
        g_ici, g_dcn = SH.edge_level_counts(plan.coords, gsched)
        assert g_dcn == 0.0, (
            "per-group schedules must never emit a cross-group (DCN) "
            f"edge, got {g_dcn}")
        if frac == 1.0:
            baseline_dcn = dcn_bytes
        else:
            ratio = dcn_bytes / baseline_dcn
            assert abs(ratio - frac) < 1e-9, (
                f"DCN bytes must scale with the replicated fraction: "
                f"frac={frac} ratio={ratio}")
        detail[f"{int(frac * 100)}%"] = {
            "replicated_fraction": frac,
            "rep_row_bytes": rep_row,
            "sharded_row_bytes": sh_row,
            "dcn_bytes_per_step": dcn_bytes,
            "dcn_vs_all_replicated": round(dcn_bytes / baseline_dcn, 4),
            "ici_bytes_per_step": rep_row * rep_ici + sh_row * g_ici,
            "group_rounds": [len(sub.rounds) for _g, sub in per_group],
            "merged_rounds": len(gsched.rounds),
        }

    # ---- Part 2: executor leg on the 8-device CPU mesh ------------------
    import jax
    import jax.numpy as jnp
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from jax.sharding import PartitionSpec as P
    import optax

    import bluefog_tpu as bf
    from bluefog_tpu.utils import config, telemetry

    knobs = ("BLUEFOG_TPU_TELEMETRY", "BLUEFOG_TPU_SHARDED_GOSSIP")
    saved = {k: os.environ.get(k) for k in knobs}
    rng = np.random.default_rng(args.seed)
    steps = 2 if smoke else 4
    e2e = {}
    try:
        os.environ["BLUEFOG_TPU_TELEMETRY"] = "1"
        os.environ.pop("BLUEFOG_TPU_SHARDED_GOSSIP", None)
        config.reload()
        bf.init()
        n8 = bf.size()
        params = {"a": jnp.asarray(rng.standard_normal((n8, 5)),
                                   jnp.float32),
                  "b": jnp.asarray(rng.standard_normal((n8, 4, 8)),
                                   jnp.float32)}
        specs = {"a": P(), "b": P(None, "tp")}
        grads = jax.tree.map(jnp.zeros_like, params)

        def drive(shard_specs, num_shards):
            opt = bf.optim.DistributedNeighborAllreduceOptimizer(
                optax.sgd(0.0), shard_specs=shard_specs,
                num_shards=num_shards)
            state = opt.init(params)
            p = params
            for _ in range(steps):
                p, state = opt.step(p, grads, state)
            return p

        telemetry.reset()
        out = drive(specs, 2)
        snap = telemetry.snapshot()

        # Dense oracle for the replicated leaf: one step is W^T x.
        W = topo.weight_matrix(bf.load_topology())
        exp_a = np.asarray(params["a"])
        for _ in range(steps):
            exp_a = W.T @ exp_a
        rep_err = float(np.abs(np.asarray(out["a"]) - exp_a).max())
        assert rep_err <= 1e-6, rep_err

        # Per-group oracle for each rank's own slice; ghost untouched.
        plan = SH.build_plan(params, specs, n=n8, n_shards=2)
        _g, per = SH.compile_group_schedules(n8, plan.groups)
        Wg = np.zeros((n8, n8))
        for g, _sub in per:
            sw = topo.weight_matrix(topo.ExponentialTwoGraph(len(g)))
            for i, gi in enumerate(g):
                for j, gj in enumerate(g):
                    Wg[gi, gj] = sw[i, j]
        b0, b1 = np.asarray(params["b"]), np.asarray(out["b"])
        chunk = b0.shape[-1] // 2
        sh_err = 0.0
        for r in range(n8):
            c = plan.coords[r]
            own = b0[:, :, c * chunk:(c + 1) * chunk]
            exp = own.copy()
            for _ in range(steps):
                exp = np.einsum("sr,s...->r...", Wg, exp)
            got = b1[r, :, c * chunk:(c + 1) * chunk]
            sh_err = max(sh_err, float(np.abs(got - exp[r]).max()))
            ghost = b1[r, :, (1 - c) * chunk:(2 - c) * chunk]
            assert np.array_equal(
                ghost, b0[r, :, (1 - c) * chunk:(2 - c) * chunk]), (
                f"rank {r}: ghost region must be bit-untouched")
        assert sh_err <= 1e-6, sh_err

        # Telemetry: DCN carries exactly the replicated rows, never a
        # sharded byte.
        plan8 = plan
        sched8 = S.compile_static(bf.load_topology())
        ici8, dcn8 = SH.edge_level_counts(plan8.coords, sched8)
        rep_row8 = plan8.rep_bytes / n8
        key_dcn = ('bf_comm_level_bytes_total'
                   '{level="dcn",shard="replicated"}')
        got_dcn = snap.get(key_dcn, 0.0)
        want_dcn = rep_row8 * dcn8 * steps
        assert abs(got_dcn - want_dcn) < 1e-6, (got_dcn, want_dcn)
        assert not any('shard="sharded"' in k and '"dcn"' in k
                       for k in snap), (
            "sharded bytes must never be billed to the DCN")

        # Bitwise hatches: knob off, and a fully replicated tree.
        base = drive(None, None)
        os.environ["BLUEFOG_TPU_SHARDED_GOSSIP"] = "0"
        config.reload()
        off = drive(specs, 2)
        os.environ.pop("BLUEFOG_TPU_SHARDED_GOSSIP", None)
        config.reload()
        allrep = drive({"a": P(), "b": P()}, 2)
        for k in base:
            assert np.array_equal(np.asarray(off[k]),
                                  np.asarray(base[k])), (
                f"{k}: BLUEFOG_TPU_SHARDED_GOSSIP=0 must be BIT-identical "
                "to the no-spec path")
            assert np.array_equal(np.asarray(allrep[k]),
                                  np.asarray(base[k])), (
                f"{k}: a fully replicated tree must be BIT-identical to "
                "the no-spec path")
        e2e = {
            "mesh": f"{n8}-device CPU, 2 replica groups of 4",
            "steps": steps,
            "replicated_oracle_max_err": rep_err,
            "sharded_oracle_max_err": sh_err,
            "dcn_bytes": got_dcn,
            "dcn_bytes_expected": want_dcn,
            "replicated_fraction": plan8.replicated_fraction,
        }
        bf.shutdown()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        config.reload()

    half = detail["50%"]
    print(json.dumps({
        "metric": "sharded_gossip_dcn_bytes_fraction_at_50pct",
        "value": half["dcn_vs_all_replicated"],
        "unit": "x",
        "detail": {"smoke": smoke, "fractions": detail, "e2e": e2e},
    }))
    return 0


def main():
    args = _parse_args()
    if args.ffi or args.ffi_smoke:
        return ffi_main(args)
    if args.fused or args.fused_smoke:
        return fused_main(args)
    if args.probe_smoke:
        return probe_main(args)
    if args.async_smoke:
        return async_main(args)
    if args.tracerec_smoke:
        return tracerec_main(args)
    if args.stripe_smoke:
        return stripe_main(args)
    if args.transport or args.transport_smoke:
        return transport_main(args)
    if args.placement or args.placement_smoke:
        return placement_main(args)
    if args.synth or args.synth_smoke:
        return synth_main(args)
    if args.hier or args.hier_smoke:
        return hier_main(args)
    if args.sharded or args.sharded_smoke:
        return sharded_main(args)
    if args.smoke:
        args.n = args.n or 8
        args.payload = min(args.payload, 1024)
        args.iters = min(args.iters, 5)
        args.reps = min(args.reps, 4)

    # Backend selection BEFORE jax import: default to CPU (this is a
    # schedule benchmark, not a bandwidth one) and size the virtual mesh
    # to the requested topology so the numeric-equivalence check runs at
    # full scale.
    platform = os.environ.get("JAX_PLATFORMS") or "cpu"
    os.environ["JAX_PLATFORMS"] = platform
    if platform == "cpu":
        n = args.n or 32
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}")

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    jax.config.update("jax_platforms", platform)
    devs = jax.devices()
    n = min(args.n or len(devs), len(devs))
    if n < 4:
        import sys
        print(f"bench_comm: needs >= 4 ranks to build its topologies, have "
              f"{n} device(s) on backend {jax.default_backend()!r}; run "
              "with JAX_PLATFORMS=cpu (the script self-sizes a virtual "
              "mesh) or pass --n on a larger mesh", file=sys.stderr)
        return 2
    mesh = Mesh(np.asarray(devs[:n]), ("r",))

    from bluefog_tpu import topology as topo
    from bluefog_tpu.ops import collective as C
    from bluefog_tpu.ops import schedule as S
    from bluefog_tpu.ops import schedule_opt as SO
    from bluefog_tpu.utils import telemetry

    # Random-regular needs n * degree even: drop the clamped degree by one
    # for parity, and fail with a usable message if that empties it.
    rr_degree = min(args.degree, n - 1)
    if (n * rr_degree) % 2:
        rr_degree -= 1
    if rr_degree < 1:
        raise SystemExit(
            f"bench_comm: no valid random-regular degree at n={n} with "
            f"--degree {args.degree} (n * degree must be even and "
            "0 < degree < n); use an even --n or a larger --degree")

    topologies = dict(_topo_families(topo, n, args.seed,
                                     degree=rr_degree))

    rng = np.random.default_rng(args.seed)
    x = jnp.asarray(rng.standard_normal((n, args.payload)), jnp.float32)

    def run_op(sched):
        def body(b):
            out = b[0]
            for _ in range(args.reps):
                out = C.neighbor_allreduce(out, sched, "r")
            return out[None]
        return jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=P("r"), out_specs=P("r"),
            check_vma=False))

    def time_op(fn):
        out = fn(x)
        jax.block_until_ready(out)  # compile + warm
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = fn(x)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        return dt / (args.iters * args.reps) * 1e3  # ms per op

    detail = {}
    for name, make in topologies.items():
        w = topo.weight_matrix(make())
        naive = S._build_schedule(w, optimize=False)
        opt = S._build_schedule(w, optimize=True)
        r0, e0 = C.schedule_wire_stats(naive)[:2]
        r1, e1 = C.schedule_wire_stats(opt)[:2]
        assert e0 == e1, f"{name}: repack changed the edge set ({e0} -> {e1})"
        assert r1 <= r0, f"{name}: repack emitted MORE rounds ({r0} -> {r1})"
        assert r1 == SO.min_rounds(opt), \
            f"{name}: {r1} rounds, König bound {SO.min_rounds(opt)}"
        f_naive, f_opt = run_op(naive), run_op(opt)
        out_naive = np.asarray(f_naive(x))
        out_opt = np.asarray(f_opt(x))
        max_diff = float(np.abs(out_naive - out_opt).max())
        assert max_diff <= 1e-6, \
            f"{name}: outputs differ by {max_diff} (> 1e-6)"
        detail[name] = {
            "rounds_naive": r0, "rounds_optimized": r1,
            "edges": e0,
            "round_reduction": round(r0 / max(r1, 1), 3),
            "ms_per_op_naive": round(time_op(f_naive), 4),
            "ms_per_op_optimized": round(time_op(f_opt), 4),
            "max_output_diff": max_diff,
        }

    rr = detail["random_regular"]
    snap = telemetry.snapshot() if telemetry.enabled() else {}
    print(json.dumps({
        "metric": "gossip_schedule_opt_round_reduction_random_regular",
        "value": rr["round_reduction"],
        "unit": "x",
        "detail": {
            "n": n,
            "payload_f32": args.payload,
            "backend": jax.default_backend(),
            "per_topology": detail,
            "schedule_opt_rounds_saved_total": snap.get(
                "bf_schedule_opt_rounds_saved_total", 0),
        },
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
