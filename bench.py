"""Headline benchmark: ResNet-50 decentralized training throughput.

Mirrors the reference's protocol (``examples/pytorch_benchmark.py:38-44,
228-256``): synthetic ImageNet data, N warmup batches, I iterations of B
batches each, report mean images/sec.  The reference's headline number is
4310.6 img/s on 16 V100s == ~269 img/s/GPU at batch 64 (BASELINE.md); here we
measure img/s per TPU chip with the same per-device batch size, running the
FULL decentralized training step (forward, backward, SGD+momentum update, and
the dynamic one-peer Exp-2 neighbor averaging) over all available devices.

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": "img/s/chip", "vs_baseline": ...}
``vs_baseline`` is per-chip throughput over the reference's 269 img/s/GPU.
"""

import json
import os
import re
import time
from functools import partial

import numpy as np

BASELINE_PER_GPU = 4310.6 / 16  # img/s per V100, reference docs/performance.rst

# Probe stderr patterns that mean "the tunnel blipped", not "the code is
# wrong": these nonzero exits retry inside the same window as init hangs
# (a libtpu RPC layer that loses the backend typically FAILS fast with one
# of these rather than hanging).
_TRANSIENT_PROBE_PAT = re.compile(
    r"(?i)connection (refused|reset|closed|aborted)|reset by peer|"
    r"unavailable|deadline[ _]?exceeded|failed to connect|"
    r"socket (closed|timeout)|temporarily unavailable|broken pipe|"
    r"transport (closed|error)|unreachable")


def _cpu_fallback_or_exit(reason: str) -> bool:
    """When the accelerator is unreachable: with
    ``BLUEFOG_TPU_BENCH_ALLOW_CPU=1`` fall back to a clearly-labeled CPU
    smoke metric (``"backend": "cpu"`` + ``"cpu_fallback"`` in the JSON —
    a data point that proves the code path, never a throughput claim)
    instead of yielding NO metric for the round (BENCH_r05: rc=3 left 3
    straight rounds without evidence); without the opt-in, exit 3 as
    before so a dead tunnel cannot print a bogus accelerator number."""
    import sys
    if os.environ.get("BLUEFOG_TPU_BENCH_ALLOW_CPU") not in (
            "1", "true", "True", "yes"):  # same spellings as config._flag
        # Still emit a BENCH artifact (status: no_backend, value null) so
        # the perf trajectory records the attempt — BENCH_r05 had three
        # rounds with NO artifact because this path printed only stderr.
        # rc stays 3: a null-valued JSON is evidence of the outage, never
        # a throughput claim a driver could mistake for success.
        print(json.dumps({
            "metric": "resnet50_train_imgs_per_sec_per_chip",
            "value": None,
            "unit": "img/s/chip",
            "status": "no_backend",
            "detail": {"reason": reason},
        }))
        raise SystemExit(3)
    print(f"bench: {reason} — BLUEFOG_TPU_BENCH_ALLOW_CPU=1 set, falling "
          "back to a CPU smoke run (metric will be labeled backend=cpu)",
          file=sys.stderr)
    os.environ["JAX_PLATFORMS"] = "cpu"
    return True


def _probe_backend(timeout_s: float = 180.0,
                   retry_window_s: float = 900.0) -> bool:
    """Fail FAST when the accelerator tunnel is down: a dead backend hangs
    jax's init inside a C call no signal can interrupt, so probe it in a
    disposable subprocess first and exit with a clear error instead of
    wedging the benchmark run for hours (observed live outage).

    A transient tunnel blip must not cost a whole round's evidence, so a
    HANG retries with backoff for up to ``retry_window_s`` (~15 min,
    override via ``BLUEFOG_TPU_BENCH_PROBE_WINDOW``); a probe that ERRORS
    (missing jax, bad platform string, crashing plugin) is deterministic
    and fails immediately.  Returns True when the run proceeds on the CPU
    fallback (see :func:`_cpu_fallback_or_exit`)."""
    import subprocess
    import sys
    retry_window_s = float(os.environ.get(
        "BLUEFOG_TPU_BENCH_PROBE_WINDOW", retry_window_s))
    deadline = time.monotonic() + retry_window_s
    delay, attempt = 30.0, 0
    last_stderr = ""
    while True:
        attempt += 1
        err = None
        # Honor an explicit JAX_PLATFORMS pin (CPU smoke runs): site hooks
        # may re-pin the accelerator platform via jax.config, which WINS
        # over the env var, so the probe must set the config knob too.
        probe_src = ("import jax, os; p = os.environ.get('JAX_PLATFORMS'); "
                     "p and jax.config.update('jax_platforms', p); "
                     "print('NDEV', len(jax.devices()))")
        try:
            ping = subprocess.run(
                [sys.executable, "-c", probe_src],
                capture_output=True, text=True, timeout=timeout_s)
            if ping.returncode == 0:
                return False
            if _TRANSIENT_PROBE_PAT.search(ping.stderr or ""):
                # A fast connection error from the plugin is as transient
                # as an init hang — same retry window.
                err = ("accelerator backend unreachable (transient "
                       "connection error)")
                last_stderr = ping.stderr or ""
            else:
                print("bench: backend probe failed (deterministic — not "
                      "retrying):\n" + ping.stderr[-2000:], file=sys.stderr)
                return _cpu_fallback_or_exit("deterministic probe failure")
        except subprocess.TimeoutExpired:
            err = "accelerator backend unreachable (init hang)"
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            print(f"bench: {err} — giving up after {attempt} attempts; "
                  "not printing a bogus accelerator metric", file=sys.stderr)
            if last_stderr:  # the operator needs the actual error text
                print("bench: last probe stderr:\n" + last_stderr[-2000:],
                      file=sys.stderr)
            return _cpu_fallback_or_exit(err)
        wait = min(delay, remaining)
        print(f"bench: {err} — retrying in {wait:.0f}s "
              f"({remaining:.0f}s left in probe window)", file=sys.stderr)
        time.sleep(wait)
        delay = min(delay * 2, 240.0)


def _placement_summary(devs, dyn) -> "dict | None":
    """Modeled placement evidence for BENCH json: identity vs optimized
    max-link-load of the benchmark's own dynamic gossip schedule on the
    interconnect the devices expose (TPU coords / BLUEFOG_TPU_FAKE_TORUS).
    Flat hosts (CPU smoke runs) get a synthetic near-square torus sized to
    the mesh, clearly labeled — a cost-model data point proving the
    optimizer path, never a hardware claim."""
    import math

    from bluefog_tpu.ops import placement as PL
    n = len(devs)
    if n < 2 or dyn is None:
        return None
    model = PL.build_model(devs)
    synthetic = model is None
    if model is None:
        r = max(int(math.isqrt(n)), 1)
        while n % r:
            r -= 1
        model = PL.synthetic_torus((r, n // r),
                                   name=f"synthetic-{r}x{n // r}")
    try:
        res = PL.optimize_placement(model, dyn, n, iters=300, seed=0)
    except ValueError:
        return None
    return {
        "model": model.name + (" (synthetic)" if synthetic else ""),
        "max_link_load_naive": res.identity_cost.max_link_load,
        "max_link_load_opt": res.optimized_cost.max_link_load,
        "improvement_ratio": round(res.improvement_ratio, 3),
    }


def _hierarchy_summary(devs, tree_bytes: float) -> "dict | None":
    """Hierarchical-gossip evidence for BENCH json: the two-level policy
    (levels, outer cadence, per-level compression) and the modeled
    per-step wire bytes of each level for THIS run's parameter tree.
    ``enabled`` mirrors ``BLUEFOG_TPU_HIER`` so the schema is stable; on
    hosts whose devices expose no slice structure a synthetic 2-slice
    split is priced and labeled (code-path evidence, never a hardware
    claim — same convention as detail.placement)."""
    from bluefog_tpu import topology
    from bluefog_tpu.utils import config
    cfg = config.get()
    n = len(devs)
    out = {"enabled": bool(cfg.hier)}
    if n < 2:
        return out
    slices = {int(getattr(d, "slice_index", 0) or 0) for d in devs}
    n_slices, synthetic = len(slices), False
    if n_slices < 2 or n % n_slices:
        if n % 2:
            return out
        n_slices, synthetic = 2, True
    try:
        ht = topology.hierarchical_two_level(
            n, n_slices, inner=cfg.hier_inner, outer=cfg.hier_outer,
            outer_every=cfg.hier_outer_every,
            outer_self_weight=cfg.hier_outer_self_weight)
    except ValueError:
        return out
    comp = cfg.hier_outer_compression
    factor = config.compression_byte_factor(comp)
    inner_edges = ht.ici_edges_per_step()
    row_bytes = float(tree_bytes) / n
    out.update({
        "levels": 2,
        "n_slices": n_slices,
        "slice_size": ht.slice_size,
        "synthetic_slices": synthetic,
        "inner": ht.inner_kind,
        "outer": ht.outer_kind,
        "outer_every": ht.outer_every,
        "outer_compression": comp,
        "outer_self_weight": ht.outer_self_weight,
        "ici_bytes_per_step": round(row_bytes * inner_edges, 1),
        "dcn_bytes_per_step": round(
            row_bytes * ht.dcn_edges_per_outer_step() * factor
            / max(ht.outer_every, 1), 1),
    })
    return out


def _sharding_summary(devs) -> "dict | None":
    """Sharded-gossip evidence for BENCH json: the ``ShardPlan`` of a
    labeled synthetic MoE tree (this bench's ResNet tree is fully
    replicated, so a synthetic tree is what exercises the planner —
    code-path evidence, same convention as detail.hierarchy's synthetic
    slices): replicated fraction, planner decisions per leaf, and the
    modeled per-level / per-shard wire bytes on THIS mesh.  ``enabled``
    mirrors ``BLUEFOG_TPU_SHARDED_GOSSIP`` so the schema is stable."""
    import numpy as np
    from bluefog_tpu import topology
    from bluefog_tpu.ops import schedule as S
    from bluefog_tpu.ops import sharded as SH
    from bluefog_tpu.utils import config
    cfg = config.get()
    n = len(devs)
    out = {"enabled": bool(cfg.sharded_gossip)}
    if n < 4 or n % 2:
        return out
    n_shards = 4 if n % 4 == 0 else 2
    tree = {
        "router": np.zeros((n, 256), np.float32),
        "experts": np.zeros((n, n_shards, 512), np.float32),
        # Indivisible model dim: the planner must fall back to
        # replicated and say so in its decision string.
        "head": np.zeros((n, 7, 16), np.float32),
    }
    specs = {"router": None, "experts": ("ep", None),
             "head": ("ep", None)}
    try:
        plan = SH.build_plan(tree, specs, n=n, n_shards=n_shards)
        sched = S.compile_static(topology.ExponentialTwoGraph(n))
        gsched, _per = SH.compile_group_schedules(n, plan.groups)
    except (ValueError, SystemExit):
        return out
    rep_ici, rep_dcn = SH.edge_level_counts(plan.coords, sched)
    g_ici, g_dcn = SH.edge_level_counts(plan.coords, gsched)
    rep_row = plan.rep_bytes / n
    sh_row = (plan.sh_bytes / n / plan.n_shards
              if plan.any_sharded else 0.0)
    out.update(plan.summary())
    out.update({
        "synthetic_tree": True,
        "bytes_per_step": {
            "replicated_ici": round(rep_row * rep_ici, 1),
            "replicated_dcn": round(rep_row * rep_dcn, 1),
            "sharded_ici": round(sh_row * g_ici, 1),
            # Always 0 by construction — in-group schedules cross no
            # replica-group boundary; kept so regressions are visible.
            "sharded_dcn": round(sh_row * g_dcn, 1),
        },
    })
    return out


def _churn_summary() -> "dict | None":
    """Churn-controller evidence for BENCH json: the live membership view
    (epoch, active ranks, change count, last change time) when
    BLUEFOG_TPU_CHURN is on, or the enabled=False stub otherwise — so a
    bench run under churn carries the gang state its numbers were measured
    against.  The single-chip bench never churns; the block exists so the
    JSON schema is stable across workloads (the chaos harness is where the
    membership actually moves)."""
    from bluefog_tpu.ops import membership
    from bluefog_tpu.utils import config
    if not config.get().churn:
        return {"enabled": False}
    m = membership.health_summary()
    if m is None:
        return {"enabled": True, "active": None}
    return {
        "enabled": True,
        "epoch": m["epoch"],
        "active_ranks": m["active_ranks"],
        "changes_total": m["changes_total"],
        "last_change_unix": m["last_change_unix"],
    }


def _links_summary() -> "dict | None":
    """Link-observatory evidence for BENCH json: the observatory gate,
    configured SLO rules, and this rank's live link table (per-edge delay
    EWMA / jitter / divergence, tx goodput) when any traced gossip ran.
    The single-chip bench's fused step never crosses the DCN window
    transport, so the table is typically empty here; the block exists so
    the JSON schema is stable across workloads (multi-proc runs and the
    chaos links harness are where the edges move), mirroring
    detail.churn."""
    from bluefog_tpu.utils import config, linkobs
    if not config.get().link_obs:
        return {"enabled": False}
    rep = linkobs.local_report()
    return {
        "enabled": True,
        "slo_rules": rep["slo"]["rules"],
        "slo_breached": sorted(rep["slo"]["breached"]),
        "edges": rep["edges"],
        "goodput": rep["goodput"],
    }


def _fused_step_summary() -> "dict | None":
    """Whole-step compilation evidence for BENCH json: with
    BLUEFOG_TPU_FUSED_STEP armed, the eager-vs-fused end-to-end step
    time (p50/p99 ms), speedup and one-time compile cost measured on
    bench_comm's loopback transport rig — the put-family twin of the
    allreduce step this bench times (which already runs as one XLA
    program).  Off by default, so the block is ``{"enabled": False}``
    unless the flag is set; capability misses (no native
    bf_xla_win_put_pass handler, non-CPU jax backend) degrade to a
    labeled skip, mirroring detail.links."""
    from bluefog_tpu.utils import config
    if not config.get().fused_step:
        return {"enabled": False}
    try:
        import bench_comm
        from bluefog_tpu import native
        from bluefog_tpu.ops import xlaffi
        if not (native.available() and native.has_win_xla()
                and native.has_xla_handler()
                and xlaffi.has_passthrough()):
            return {"enabled": True,
                    "skipped": "native bf_xla_win_put_pass unavailable"}
        prev = bench_comm._fused_env_setup()
        try:
            config.reload()
            xlaffi._reset_for_tests()
            if not xlaffi.armed():
                return {"enabled": True,
                        "skipped": xlaffi.disarm_reason() or "disarmed"}
            cell = bench_comm._fused_timing_cell(steps=20, warm=4)
        finally:
            bench_comm._fused_env_restore(prev)
        return {"enabled": True, **cell}
    except Exception as e:  # noqa: BLE001 — evidence block, never fatal
        return {"enabled": True, "skipped": f"rig unavailable: {e}"}


def _synthesis_summary(devs) -> "dict | None":
    """Modeled schedule-synthesis evidence for BENCH json, matching the
    placement pattern: the flagship STATIC Exp2 gossip schedule priced on
    the interconnect the devices expose (synthetic near-square torus on
    flat hosts, labeled), comparing the congestion-packed baseline against
    the sketch-synthesized selection on serial_link_time.  The one-peer
    dynamic schedule the bench actually steps is single-round per phase
    (nothing to synthesize); the static schedule is where the modeled-comm
    win lives and what multi-round deployments dispatch."""
    import math

    from bluefog_tpu import topology
    from bluefog_tpu.ops import placement as PL
    from bluefog_tpu.ops import schedule as S
    from bluefog_tpu.ops import schedule_opt as SO
    from bluefog_tpu.ops import synthesis as SY
    n = len(devs)
    if n < 4:
        return None
    model = PL.build_model(devs)
    synthetic = model is None
    if model is None:
        r = max(int(math.isqrt(n)), 1)
        while n % r:
            r -= 1
        model = PL.synthetic_torus((r, n // r),
                                   name=f"synthetic-{r}x{n // r}")
    try:
        w = topology.weight_matrix(topology.ExponentialTwoGraph(n))
        naive = S._build_schedule(w, optimize=False)
        sched = SO.optimize_schedule(naive)
        packed = SO.congestion_aware_repack(sched, model, None,
                                            budget_factor=2.0,
                                            record=False)
        chosen, ratio = SY.select_schedule(sched, packed, model, None)
    except ValueError:
        return None
    return {
        "model": model.name + (" (synthetic)" if synthetic else ""),
        "sketch": getattr(chosen, "sketch", None),
        "provenance": S.schedule_provenance(chosen),
        "serial_naive": PL.schedule_cost(model, naive).serial_link_time,
        "serial_konig": PL.schedule_cost(model, sched).serial_link_time,
        "serial_packed": PL.schedule_cost(model, packed).serial_link_time,
        "serial_synth": PL.schedule_cost(model, chosen).serial_link_time,
        "improvement_ratio": round(ratio, 3),
    }


def main():
    cpu_fallback = _probe_backend()
    import jax
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp
    import optax
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from bluefog_tpu import topology
    from bluefog_tpu.models import ResNet50
    from bluefog_tpu.ops import schedule as S
    from bluefog_tpu.optim import functional as F

    devs = jax.devices()
    n = len(devs)
    on_tpu = jax.default_backend() == "tpu"
    # Reference protocol on accelerators (batch raised 64 -> 256: the step is
    # HBM-bandwidth-bound, and larger batches amortize the per-step parameter
    # and BN-statistics traffic — +4.5% over 128, measured; see
    # docs/performance.md profile). Tiny smoke scale on CPU.
    batch = 256 if on_tpu else 2
    image = 224 if on_tpu else 64
    warmup, iters, batches_per_iter = (10, 10, 10) if on_tpu else (1, 2, 2)

    mesh = Mesh(np.asarray(devs), ("dp",))
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)

    images = jnp.zeros((n * batch, image, image, 3), jnp.bfloat16)
    labels = jnp.zeros((n * batch,), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), images[:2])
    params0, batch_stats0 = variables["params"], variables["batch_stats"]
    rank_major = lambda t: jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), t)
    params, batch_stats = rank_major(params0), rank_major(batch_stats0)

    base = optax.sgd(0.0125 * n, momentum=0.9)
    dyn = S.compile_dynamic(topology.one_peer_exp2_phases(n), n) if n > 1 else None
    combine = F.make_combiner(
        F.CommunicationType.neighbor_allreduce if n > 1
        else F.CommunicationType.empty, axis_name="dp", dyn_sched=dyn)
    # BLUEFOG_TPU_BENCH_COMPRESSION: none (default) | bf16 | sparse:<frac>.
    # sparse composes with the flagship dynamic one-peer Exp2 schedule (the
    # rotating aligned block rides the same lax.switch of phases).
    compression = os.environ.get("BLUEFOG_TPU_BENCH_COMPRESSION", "none")
    combine = F.compress_combiner(combine, compression)

    def local_step(p, bs, st, images, labels, *, reduce_loss):
        def loss_fn(p):
            logits, new_model_state = model.apply(
                {"params": p, "batch_stats": bs}, images, train=True,
                mutable=["batch_stats"])
            logp = jax.nn.log_softmax(logits)
            loss = -jnp.take_along_axis(logp, labels[:, None], -1).mean()
            return loss, new_model_state["batch_stats"]

        (loss, new_bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(p)
        new_p, new_st = F.atc_step(base, combine, p, grads, st)
        return new_p, new_bs, new_st, (lax.pmean(loss, "dp")
                                       if reduce_loss else loss)

    if n == 1:
        # Single chip: no rank-major wrapper, no shard_map (it costs ~20% at
        # n=1 and the combine is identity anyway).
        params, batch_stats = params0, batch_stats0
        state = jax.jit(lambda p: F.dist_init(base, p))(params)
        step = jax.jit(partial(local_step, reduce_loss=False),
                       donate_argnums=(0, 1, 2))
    else:
        def train_step(params, batch_stats, state, images, labels):
            p, bs, st = jax.tree.map(lambda x: x[0],
                                     (params, batch_stats, state))
            new_p, new_bs, new_st, loss = local_step(
                p, bs, st, images, labels, reduce_loss=True)
            return (jax.tree.map(lambda x: x[None], new_p),
                    jax.tree.map(lambda x: x[None], new_bs),
                    jax.tree.map(lambda x: x[None], new_st), loss)

        def init_state(params):
            st = F.dist_init(base, jax.tree.map(lambda x: x[0], params))
            return jax.tree.map(lambda x: x[None], st)

        state = jax.jit(jax.shard_map(
            init_state, mesh=mesh, in_specs=(P("dp"),),
            out_specs=P("dp")))(params)
        step = jax.jit(
            jax.shard_map(
                train_step, mesh=mesh,
                in_specs=(P("dp"), P("dp"), P("dp"), P("dp"), P("dp")),
                out_specs=(P("dp"), P("dp"), P("dp"), P()),
                check_vma=False),
            donate_argnums=(0, 1, 2))

    data_sharding = NamedSharding(mesh, P("dp"))
    images = jax.device_put(images, data_sharding)
    labels = jax.device_put(labels, data_sharding)

    # Sync by fetching a scalar that depends on the UPDATED params: on some
    # remote-tunnel platforms block_until_ready returns before the device
    # finishes, so only a host read-back is a true barrier.
    probe = jax.jit(lambda p, l: jnp.sum(
        jax.tree_util.tree_leaves(p)[0].astype(jnp.float32)) * 0 + l)

    def sync():
        return float(probe(params, loss))

    for _ in range(warmup):
        params, batch_stats, state, loss = step(
            params, batch_stats, state, images, labels)
    sync()

    # Per-phase latency histograms (utils/telemetry.observe): dispatch
    # wall time per step ("optimizer-update" — the whole fused program's
    # python-side cost) and the per-iteration device sync ("host-sync"),
    # so BENCH json carries p50/p99 TAIL evidence, not just the mean rate.
    # A bench-OWNED series: these definitions differ from the step
    # profiler's canonical bf_step_phase_seconds attribution and must not
    # pollute it.
    from bluefog_tpu.utils import telemetry
    rates = []
    for _ in range(iters):
        t0 = time.perf_counter()
        for _ in range(batches_per_iter):
            t_step = time.perf_counter()
            params, batch_stats, state, loss = step(
                params, batch_stats, state, images, labels)
            telemetry.observe("bf_bench_phase_seconds",
                              time.perf_counter() - t_step,
                              phase="optimizer-update")
        t_sync = time.perf_counter()
        sync()
        telemetry.observe("bf_bench_phase_seconds",
                          time.perf_counter() - t_sync, phase="host-sync")
        dt = time.perf_counter() - t0
        rates.append(n * batch * batches_per_iter / dt)

    total = float(np.mean(rates))
    per_chip = total / n

    # Comm-counter evidence for BENCH_*.json: the training step is ONE
    # fused XLA program, so the host-side dispatch counters never fire
    # inside it — record the schedule-derived traffic through the same
    # telemetry registry instead (calls = executed steps; wire bytes from
    # the per-rank parameter row size and the dynamic schedule's per-call
    # round/edge average) and ship the snapshot in the JSON.
    from bluefog_tpu.ops import collective as C
    steps_run = warmup + iters * batches_per_iter
    tree_bytes = float(sum(x.nbytes for x in jax.tree_util.tree_leaves(
        params)))
    op = "dynamic_neighbor_allreduce" if dyn is not None else "local_sgd"
    telemetry.record_comm_traffic(
        op, tree_bytes, size=n, calls=steps_run,
        sched_stats=None if dyn is None else C.schedule_wire_stats(dyn))
    snap = telemetry.snapshot() if telemetry.enabled() else None

    # Tail-latency trajectory for future rounds: per-phase p50/p99 (ms)
    # from the new step-phase histograms (None when telemetry is off).
    phase_latency = {}
    for ph in ("optimizer-update", "host-sync"):
        pct = telemetry.histogram_percentiles(
            "bf_bench_phase_seconds", (50.0, 99.0), phase=ph)
        if pct:
            phase_latency[ph] = {"p50_ms": round(pct[50.0] * 1e3, 3),
                                 "p99_ms": round(pct[99.0] * 1e3, 3)}

    print(json.dumps({
        "metric": "resnet50_train_imgs_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "img/s/chip",
        "vs_baseline": round(per_chip / BASELINE_PER_GPU, 3),
        "detail": {
            "total_imgs_per_sec": round(total, 1),
            "n_devices": n,
            "per_device_batch": batch,
            "image_size": image,
            "backend": jax.default_backend(),
            "stddev_pct": round(100 * float(np.std(rates)) / max(total, 1e-9), 2),
            "optimizer": "ATC neighbor_allreduce (dynamic one-peer Exp2)"
            if n > 1 else "local SGD (single chip)",
            "compression": compression,
            # Accelerator tunnel was down; this is a CPU smoke data point
            # (code-path evidence only), never a throughput claim.
            "cpu_fallback": cpu_fallback,
            "phase_latency": phase_latency or None,
            "placement": _placement_summary(devs, dyn),
            "synthesis": _synthesis_summary(devs),
            "hierarchy": _hierarchy_summary(devs, tree_bytes),
            "sharding": _sharding_summary(devs),
            "churn": _churn_summary(),
            "links": _links_summary(),
            "fused_step": _fused_step_summary(),
            "telemetry": snap,
        },
    }))


if __name__ == "__main__":
    main()
