"""Sequence-parallel long-context LM training over a device mesh.

No reference counterpart (SURVEY §5.7: BlueFog predates LLM-era sequence
scaling).  This example trains a TransformerLM whose SEQUENCE axis is
sharded across the mesh: each device holds ``seq_len / n`` tokens, ring
attention (``parallel.ring_attention``) streams K/V blocks around the mesh
so no device ever materializes full-sequence logits or K/V, and the data-
parallel axis is dropped in favor of one long stream — the configuration
for contexts that do not fit a single chip.

    # 8 virtual devices, 8k tokens, each device holds 1k
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/long_context_training.py --seq-len 8192

On a real pod, the same code with `--attention ulysses` uses all-to-all
head parallelism instead; both compose with `--rope` (positions flow
explicitly, so every shard embeds its own offsets).
"""

import argparse


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq-len", type=int, default=4096)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--attention", choices=["ring", "ulysses"],
                    default="ring")
    ap.add_argument("--rope", action="store_true")
    args = ap.parse_args()
    if args.steps < 2:
        ap.error("--steps must be >= 2 (the run asserts the loss fell)")

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from bluefog_tpu import models
    from bluefog_tpu.parallel import ring_attention_impl, ulysses_attention_impl

    devs = jax.devices()
    n = len(devs)
    S = args.seq_len
    assert S % n == 0, f"seq-len {S} must divide over {n} devices"
    mesh = Mesh(np.asarray(devs), ("sp",))

    cfg = models.TransformerConfig(
        vocab_size=args.vocab, num_layers=2, num_heads=8, embed_dim=128,
        max_seq_len=S, dtype=jnp.float32,
        pos_encoding="rope" if args.rope else "learned")
    impl = (ring_attention_impl("sp") if args.attention == "ring"
            else ulysses_attention_impl("sp"))
    model = models.TransformerLM(cfg, attn_impl=impl)

    # A learnable synthetic language: next token = (cur * 3 + 1) % vocab,
    # with occasional noise — perplexity falls fast if training works.
    rng = np.random.RandomState(0)
    toks = np.zeros(S + 1, np.int32)
    for i in range(S):
        toks[i + 1] = (toks[i] * 3 + 1) % args.vocab \
            if rng.rand() > 0.05 else rng.randint(args.vocab)
    tokens = jnp.asarray(toks[:S])[None, :]
    targets = jnp.asarray(toks[1:S + 1])[None, :]
    positions = jnp.arange(S)[None, :]

    # init with the dense twin — attn_impl does not change the params
    params = models.TransformerLM(cfg).init(
        jax.random.PRNGKey(0), tokens[:, :16])
    opt = optax.adam(args.lr)
    state = opt.init(params)

    # The whole forward runs INSIDE shard_map: every array the model sees
    # is its sequence shard, ring/Ulysses collectives ride the "sp" axis,
    # and params (spec P()) replicate.
    seq_sharding = NamedSharding(mesh, P(None, "sp"))
    tokens = jax.device_put(tokens, seq_sharding)
    targets = jax.device_put(targets, seq_sharding)
    positions = jax.device_put(positions, seq_sharding)

    def local_loss(p, tok, pos, tgt):
        logits = model.apply(p, tok, positions=pos)
        local_sum = optax.softmax_cross_entropy_with_integer_labels(
            logits, tgt).sum()
        return jax.lax.psum(local_sum, "sp") / S

    sharded_loss = jax.shard_map(
        local_loss, mesh=mesh,
        in_specs=(P(), P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(), check_vma=False)

    @jax.jit
    def step(p, s):
        l, g = jax.value_and_grad(
            lambda p: sharded_loss(p, tokens, positions, targets))(p)
        up, s = opt.update(g, s, p)
        return optax.apply_updates(p, up), s, l

    l0 = None
    for i in range(args.steps):
        params, state, loss = step(params, state)
        if i == 0:
            l0 = float(loss)
        if (i + 1) % 10 == 0:
            print(f"step {i + 1}  loss {float(loss):.4f} "
                  f"({S} tokens over {n} devices, {args.attention})")
    lf = float(loss)
    assert lf < l0, (l0, lf)
    how = (f"ring attention streamed K/V around the mesh — no device "
           f"materialized the {S}x{S} score matrix"
           if args.attention == "ring" else
           f"Ulysses all-to-all gave each device all {S} tokens for "
           f"{cfg.num_heads}/{n} of the heads")
    print(f"done: loss {l0:.4f} -> {lf:.4f}; per-device sequence shard "
          f"{S // n} tokens; {how}")


if __name__ == "__main__":
    main()
