"""Preemption-tolerant decentralized training with ``run_elastic``.

There is no reference counterpart: BlueFog lists fault tolerance as a goal
(``README.rst:19``) but a dead rank simply shuts the job down
(``operations.cc:883-910``).  Here the training loop is restartable — run
this script, kill it (or let the cloud preempt the VM), run it again with
the same ``--ckpt-dir``: it resumes from the newest durable checkpoint and
the final model is bit-identical to an uninterrupted run.

    python examples/elastic_training.py --ckpt-dir /tmp/elastic_demo
    # ... ctrl-C / SIGTERM / VM preemption ...
    python examples/elastic_training.py --ckpt-dir /tmp/elastic_demo

``--preempt-at-step N`` sends the process a SIGTERM from inside (self-test
mode demonstrating the save-on-preemption path).
"""

import argparse
import os
import signal


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--save-every", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--preempt-at-step", type=int, default=0)
    ap.add_argument("--optimizer", choices=["neighbor_allreduce",
                                            "push_sum"],
                    default="neighbor_allreduce",
                    help="push_sum: async window gossip — the window "
                         "store (staging mass, associated-P) rides the "
                         "checkpoint via win_state_dict, so resume is "
                         "bit-exact for the one-sided family too")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import bluefog_tpu as bf
    from bluefog_tpu.models import MLP
    from bluefog_tpu.utils.elastic import Preempted, run_elastic

    bf.init()
    n = bf.size()

    # Deterministic synthetic regression task, sharded statically per rank.
    rng = np.random.RandomState(0)
    xs = rng.randn(n * 512, 16).astype(np.float32)
    w_true = rng.randn(16, 1).astype(np.float32)
    ys = xs @ w_true + 0.01 * rng.randn(n * 512, 1).astype(np.float32)
    loader = bf.data.ShardedLoader({"x": xs, "y": ys},
                                   batch_size=args.batch_size, seed=3,
                                   static_shards=True)

    model = MLP(features=(64,), num_classes=1)  # 1 output: regression head
    p0 = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16)))
    params = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), p0)
    if args.optimizer == "push_sum":
        # Push-sum needs a topology whose out-degrees drive the
        # column-stochastic split; a directed ring keeps it simple.
        bf.set_topology(bf.topology_util.RingGraph(n, connect_style=2))
        opt = bf.optim.DistributedPushSumOptimizer(optax.sgd(args.lr))
    else:
        opt = bf.optim.DistributedNeighborAllreduceOptimizer(
            optax.adam(args.lr))

    def loss_fn(p, x, y):
        return jnp.mean((model.apply(p, x) - y) ** 2)

    grad_all = jax.jit(jax.vmap(jax.grad(loss_fn)))
    steps_per_epoch = loader.steps_per_epoch

    # Data order is derived from the step, so resume replays the same
    # batches (epoch = step // steps_per_epoch). The example materializes
    # each epoch's batches; a streaming job would re-iterate the loader.
    cache = {"epoch": -1, "batches": None}

    push_sum = args.optimizer == "push_sum"

    def step_fn(state, step):
        epoch = step // steps_per_epoch
        if cache["epoch"] != epoch:
            loader.set_epoch(epoch)
            cache["epoch"], cache["batches"] = epoch, list(loader)
        batch = cache["batches"][step % steps_per_epoch]
        at = opt.debias(state["params"]) if push_sum else state["params"]
        grads = grad_all(at, batch["x"], batch["y"])
        new_p, new_s = opt.step(state["params"], grads, state["opt"])
        out = {"params": new_p, "opt": new_s}
        if push_sum:
            out["win"] = state["win"]  # placeholder; refreshed at save time
        return out

    def on_save(state, step):
        if not push_sum:
            return state
        # The window store (staging mass + associated-P) is side-band state
        # the params pytree cannot carry: snapshot it at SAVE time only (a
        # per-step snapshot would copy every window each step for nothing).
        return {**state, "win": opt.window_state_dict()}

    def on_restore(state, step):
        if push_sum:
            opt.load_window_state_dict(state["win"])

    def report(state, step):
        if args.preempt_at_step and step + 1 == args.preempt_at_step:
            os.kill(os.getpid(), signal.SIGTERM)
        if (step + 1) % args.save_every == 0:
            p = opt.debias(state["params"]) if push_sum else state["params"]
            loss = float(jax.vmap(loss_fn)(
                p, jnp.asarray(xs.reshape(n, -1, 16)),
                jnp.asarray(ys.reshape(n, -1, 1))).mean())
            print(f"step {step + 1}  mean rank loss {loss:.5f}", flush=True)

    state0 = {"params": params, "opt": opt.init(params)}
    if push_sum:
        state0["win"] = opt.window_state_dict()
    try:
        final = run_elastic(step_fn, state0, ckpt_dir=args.ckpt_dir,
                            num_steps=args.steps,
                            save_every=args.save_every, on_step=report,
                            on_restore=on_restore, on_save=on_save)
    except Preempted as e:
        print(f"preempted; checkpoint saved at step {e.step} — rerun with "
              f"the same --ckpt-dir to resume")
        raise SystemExit(75)
    eval_p = opt.debias(final["params"]) if push_sum else final["params"]
    loss = float(jax.vmap(loss_fn)(
        eval_p, jnp.asarray(xs.reshape(n, -1, 16)),
        jnp.asarray(ys.reshape(n, -1, 1))).mean())
    if push_sum:
        opt.free()
        bf.turn_off_win_ops_with_associated_p()
    print(f"done: {args.steps} steps, final mean rank loss {loss:.5f}")


if __name__ == "__main__":
    main()
