"""Throughput benchmark harness — the reference's headline experiment.

Parity: ``examples/pytorch_benchmark.py`` (model choice, synthetic data,
--dist-optimizer grid, 10-warmup / num-iters x num-batches-per-iter protocol,
mean +- 1.96 sigma reporting).  Runs the FULL decentralized training step over
every visible device.

    python examples/benchmark.py --model resnet50 --batch-size 64 \
        --dist-optimizer neighbor_allreduce

``--efficiency`` reports scaling efficiency — n-device throughput over n x
single-device throughput, the reference's headline scaling metric
(``examples/pytorch_benchmark.py:228-256`` totals img/sec across workers; the
paper reports it relative to one worker).  Single-process only: it compares
the devices this process owns against one of them.  On a multi-host pod,
run the benchmark once per world size instead and divide the totals — the
harness prints the absolute numbers either way.
"""

import argparse
import time

import numpy as np


def build_parser():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50",
                    choices=["resnet18", "resnet34", "resnet50", "resnet101",
                             "resnet152", "vgg11", "vgg16", "vgg19",
                             "lenet", "vit", "transformer"])
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-warmup-batches", type=int, default=10)
    ap.add_argument("--num-iters", type=int, default=10)
    ap.add_argument("--num-batches-per-iter", type=int, default=10)
    ap.add_argument("--dist-optimizer", default="neighbor_allreduce",
                    choices=["neighbor_allreduce", "allreduce",
                             "gradient_allreduce", "hierarchical",
                             "win_put", "empty"])
    ap.add_argument("--atc", action="store_true",
                    help="adapt-then-combine order (default AWC)")
    ap.add_argument("--compression", default="none",
                    choices=["none", "bf16"],
                    help="wire compression for the optimizer's collectives")
    ap.add_argument("--dynamic", action="store_true",
                    help="dynamic one-peer Exp2 topology")
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--seq-len", type=int, default=1024)
    ap.add_argument("--metrics-file", default=None,
                    help="append per-iter throughput as JSONL "
                         "(utils.metrics.MetricsWriter)")
    ap.add_argument("--host-data", action="store_true",
                    help="feed each batch from HOST memory through the "
                         "prefetching input pipeline (data.prefetch_to_"
                         "device) instead of device-resident tensors — "
                         "measures end-to-end throughput incl. host->HBM "
                         "transfer overlap")
    ap.add_argument("--efficiency", action="store_true",
                    help="also measure 1-device throughput and report "
                         "n-device scaling efficiency")
    ap.add_argument("--flash-attention", action="store_true",
                    help="transformer model: use the Pallas flash-attention "
                         "kernel (compiled Mosaic on TPU) instead of dense")
    ap.add_argument("--remat", action="store_true",
                    help="transformer model: jax.checkpoint each block "
                         "(recompute activations in backward; long-context "
                         "memory knob)")
    ap.add_argument("--remat-policy", default="full",
                    help="with --remat: 'full' recomputes everything; "
                         "'dots' saves matmul outputs and recomputes only "
                         "elementwise/attention; 'dots:<K>' applies dots "
                         "to the first K blocks and full to the rest (the "
                         "continuous HBM/MFU dial for models where "
                         "all-dots exceeds memory)")
    ap.add_argument("--chunked-loss", action="store_true",
                    help="transformer model: chunked lm-head cross-entropy "
                         "(never materializes the S x vocab logits)")
    ap.add_argument("--num-experts", type=int, default=0,
                    help="transformer model: switch-MoE blocks with this "
                         "many experts (0 = dense MLP)")
    ap.add_argument("--num-kv-heads", type=int, default=0,
                    help="transformer model: grouped-query attention with "
                         "this many K/V heads (0 = MHA, 1 = MQA)")
    ap.add_argument("--rope", action="store_true",
                    help="transformer model: rotary position embeddings "
                         "instead of a learned table")
    ap.add_argument("--swiglu", action="store_true",
                    help="transformer model: SwiGLU MLP instead of GELU")
    ap.add_argument("--num-layers", type=int, default=4,
                    help="transformer model: number of blocks")
    ap.add_argument("--embed-dim", type=int, default=512,
                    help="transformer model: model width")
    ap.add_argument("--num-heads", type=int, default=8,
                    help="transformer model: attention heads")
    ap.add_argument("--vocab-size", type=int, default=32000)
    ap.add_argument("--momentum", type=float, default=0.9,
                    help="SGD momentum (0 drops the accumulator — one "
                         "params-sized buffer, matters for billion-param "
                         "configs on one chip)")
    ap.add_argument("--mfu", action="store_true",
                    help="transformer model: also report model FLOPs "
                         "utilization from the measured tok/s")
    ap.add_argument("--peak-tflops", type=float, default=197.0,
                    help="accelerator peak (bf16) TFLOP/s for --mfu "
                         "(default: TPU v5e)")
    return ap


def transformer_train_flops_per_token(args, params_total: int) -> float:
    """Training FLOPs per token: 6*N for the parameter matmuls (fwd 2N +
    bwd 4N) plus the attention scores/values term 12*L*S*d (*0.5 causal),
    the standard PaLM-appendix accounting."""
    attn = 12 * args.num_layers * args.seq_len * args.embed_dim * 0.5
    return 6.0 * params_total + attn


def measure(args, devices=None, quiet=False):
    """Run the benchmark over ``devices`` (default: all) and return
    ``(mean_rate, ci, n_devices)`` where rate is samples/sec across devices."""
    import jax
    import jax.numpy as jnp
    import optax

    import bluefog_tpu as bf
    from bluefog_tpu import models
    from bluefog_tpu.optim import CommunicationType

    local_size = None
    if args.dist_optimizer == "hierarchical":
        ndev = len(devices) if devices is not None else len(jax.devices())
        local_size = max(1, ndev // 2)
    bf.init(devices=devices, local_size=local_size)
    n = bf.size()

    attn = None
    if args.flash_attention:
        from bluefog_tpu.ops.flash_attention import flash_attention_impl
        attn = flash_attention_impl()

    if args.model.startswith(("resnet", "vgg")):
        name = args.model.replace("resnet", "ResNet").replace("vgg", "VGG")
        model = getattr(models, name)(num_classes=1000, dtype=jnp.bfloat16)
        data = jnp.zeros((n, args.batch_size, args.image_size,
                          args.image_size, 3), jnp.bfloat16)
        labels = jnp.zeros((n, args.batch_size), jnp.int32)
        has_bn = args.model.startswith("resnet")  # classic VGG has no BN
    elif args.model == "lenet":
        model = models.LeNet5()
        data = jnp.zeros((n, args.batch_size, 28, 28, 1))
        labels = jnp.zeros((n, args.batch_size), jnp.int32)
        has_bn = False
    elif args.model == "vit":
        model = models.ViT(num_classes=1000, image_size=args.image_size,
                           dtype=jnp.bfloat16, remat=args.remat,
                           remat_policy=args.remat_policy, attn_impl=attn)
        data = jnp.zeros((n, args.batch_size, args.image_size,
                          args.image_size, 3), jnp.bfloat16)
        labels = jnp.zeros((n, args.batch_size), jnp.int32)
        has_bn = False
    else:
        cfg = models.TransformerConfig(
            vocab_size=args.vocab_size, num_layers=args.num_layers,
            num_heads=args.num_heads, embed_dim=args.embed_dim,
            max_seq_len=args.seq_len, remat=args.remat,
            remat_policy=args.remat_policy,
            num_experts=args.num_experts,
            num_kv_heads=args.num_kv_heads or None,
            pos_encoding="rope" if args.rope else "learned",
            mlp="swiglu" if args.swiglu else "gelu")
        model = models.TransformerLM(cfg, attn_impl=attn)
        data = jnp.zeros((n, args.batch_size, args.seq_len), jnp.int32)
        labels = None
        has_bn = False

    sample = data[0][:2]
    variables = model.init(jax.random.PRNGKey(0), sample)
    # Stashed for --mfu reporting in main() (measure()'s return shape is
    # pinned by callers).
    args._params_total = sum(
        int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(
            variables["params"] if "params" in variables else variables))
    rank_major = lambda t: jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), t)

    comm = {"neighbor_allreduce": CommunicationType.neighbor_allreduce,
            "allreduce": CommunicationType.allreduce,
            "hierarchical": CommunicationType.hierarchical_neighbor_allreduce,
            "empty": CommunicationType.empty}.get(args.dist_optimizer)
    base = optax.sgd(0.0125 * n, momentum=args.momentum or None)
    if args.dist_optimizer == "gradient_allreduce":
        opt = bf.optim.DistributedGradientAllreduceOptimizer(
            base, compression=args.compression, donate=True)
    elif args.dist_optimizer == "win_put":
        # Window payloads compress through the transport knob.  Set it
        # unconditionally so "--compression none" overrides a pre-set env
        # var and repeated in-process measure() calls stay self-consistent.
        import os
        from bluefog_tpu.utils import config as _config
        os.environ["BLUEFOG_TPU_WIN_COMPRESSION"] = args.compression
        _config.reload()
        if args.compression != "none" and jax.process_count() == 1:
            print("note: window compression applies to CROSS-PROCESS edges "
                  "only; this single-process run sends nothing over the "
                  "transport, so the flag does not change the measurement")
        opt = bf.optim.DistributedWinPutOptimizer(base)
    else:
        cls = (bf.optim.DistributedAdaptThenCombineOptimizer if args.atc
               else bf.optim.DistributedAdaptWithCombineOptimizer)
        # donate: the loop rebinds params/state every batch, so the step
        # may alias them — one params-sized buffer saved, decisive at
        # billion-parameter scale.
        opt = cls(base, comm, use_dynamic_topology=args.dynamic,
                  compression=args.compression, donate=True)

    if has_bn:
        params = rank_major(variables["params"])
        bstats = rank_major(variables["batch_stats"])

        def loss_fn(p, bs, x, y):
            logits, new = model.apply({"params": p, "batch_stats": bs},
                                      x, train=True, mutable=["batch_stats"])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean(), new["batch_stats"]

        vgrad = jax.jit(jax.vmap(jax.value_and_grad(loss_fn, has_aux=True)))

        def one_batch(params, bstats, state, batch):
            x, y = batch
            (_, bstats), grads = vgrad(params, bstats, x, y)
            params, state = opt.step(params, grads, state)
            return params, bstats, state
    else:
        params = rank_major(variables["params"] if "params" in variables
                            else variables)
        if args.model == "transformer" and args.chunked_loss:
            from bluefog_tpu.ops.chunked_loss import \
                chunked_softmax_cross_entropy

            def loss_fn(p, x, _):
                tree = {"params": p} if "params" in variables else p
                h = model.apply(tree, x, return_hidden=True)
                # p is the params mapping in either branch
                kernel = p["lm_head"]["kernel"]
                tgt = jnp.roll(x, -1, axis=1)
                return chunked_softmax_cross_entropy(h, kernel, tgt)
        elif args.model == "transformer":
            def loss_fn(p, x, _):
                logits = model.apply(
                    {"params": p} if "params" in variables else p, x)
                tgt = jnp.roll(x, -1, axis=1)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, tgt).mean()
        else:
            def loss_fn(p, x, y):
                logits = model.apply(
                    {"params": p} if "params" in variables else p, x)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, y).mean()

        vgrad = jax.jit(jax.vmap(jax.grad(loss_fn)))
        bstats = None

        def one_batch(params, bstats, state, batch):
            x, y = batch
            grads = vgrad(params, x, y)
            params, state = opt.step(params, grads, state)
            return params, bstats, state

    if args.host_data:
        # Realistic feed: batches start in host RAM and ride the input
        # pipeline; prefetch depth 2 overlaps the transfer with compute.
        # device_put always transfers afresh, so one host copy suffices.
        from bluefog_tpu.data import prefetch_to_device
        host_batch = (np.array(data),
                      None if labels is None else np.array(labels))

        def _gen():
            while True:
                yield host_batch

        feed = prefetch_to_device(_gen(), size=2)
        next_batch = lambda: next(feed)  # noqa: E731
    else:
        device_batch = (data, labels)
        next_batch = lambda: device_batch  # noqa: E731

    state = opt.init(params)

    def sync(params):
        leaf = jax.tree_util.tree_leaves(params)[0]
        float(jnp.sum(leaf[..., :1].astype(jnp.float32)))

    for _ in range(args.num_warmup_batches):
        params, bstats, state = one_batch(params, bstats, state,
                                          next_batch())
    sync(params)

    rates = []
    writer = None
    if args.metrics_file and not quiet:
        from bluefog_tpu.utils.metrics import MetricsWriter
        writer = MetricsWriter(args.metrics_file)
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            params, bstats, state = one_batch(params, bstats, state,
                                              next_batch())
        sync(params)
        dt = time.perf_counter() - t0
        rate = n * args.batch_size * args.num_batches_per_iter / dt
        rates.append(rate)
        if writer is not None:
            writer.log(step=i, imgs_per_sec=rate, model=args.model,
                       n_devices=n)
        if not quiet:
            print(f"iter {i}: {rate:.1f} img/sec across {n} devices")
    if writer is not None:
        writer.close()

    return float(np.mean(rates)), 1.96 * float(np.std(rates)), n


def main():
    args = build_parser().parse_args()
    import jax

    mean, ci, n = measure(args)
    unit = "tokens" if args.model == "transformer" else "img"
    if args.model == "transformer":
        mean, ci = mean * args.seq_len, ci * args.seq_len
    print(f"total {unit}/sec: {mean:.1f} +- {ci:.1f} "
          f"({mean / n:.1f}/device, model={args.model}, "
          f"optimizer={args.dist_optimizer})")

    if args.mfu and args.model == "transformer":
        if args.num_experts:
            # Switch MoE activates one expert per token; 6*N over ALL
            # expert weights would overstate FLOPs/token several-fold.
            print("note: --mfu accounting covers dense models only "
                  "(top-1 MoE activates 1 of --num-experts expert MLPs "
                  "per token); skipping the MFU report")
        else:
            fpt = transformer_train_flops_per_token(args, args._params_total)
            mfu = mean / n * fpt / (args.peak_tflops * 1e12)
            print(f"params: {args._params_total/1e9:.3f}B  "
                  f"train FLOPs/token: {fpt/1e9:.2f}G  "
                  f"MFU: {100*mfu:.1f}% of {args.peak_tflops:.0f} "
                  "TFLOP/s/chip")

    if args.efficiency and n > 1:
        mean1, _, _ = measure(args, devices=jax.devices()[:1], quiet=True)
        if args.model == "transformer":
            mean1 = mean1 * args.seq_len
        eff = mean / (n * mean1)
        print(f"single-device {unit}/sec: {mean1:.1f}")
        print(f"scaling efficiency at {n} devices: {100 * eff:.1f}% "
              f"({mean:.1f} vs {n} x {mean1:.1f})")
    elif args.efficiency:
        print("scaling efficiency: only one device visible; nothing to compare")


if __name__ == "__main__":
    main()
