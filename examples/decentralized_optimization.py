"""Decentralized optimization algorithm library (BASELINE config coverage).

Parity: reference ``examples/pytorch_optimization.py`` — solves a distributed
logistic regression / least squares with the classic decentralized algorithm
family, each rank holding a private data shard:

  * diffusion (adapt-then-combine over a doubly-stochastic topology)
  * exact diffusion (EXTRA-style bias correction; converges to the exact
    global minimizer under constant step size, reference ``:175-246``)
  * gradient tracking (DIGing/NEXT/Aug-DGM family, reference ``:249-361``)
  * push-DIGing (gradient tracking over DIRECTED graphs via push-sum,
    reference ``:364-444``)

All four are expressed as rank-major eager loops over the framework's
neighbor ops — the same surface a user writes.
"""

import argparse

import numpy as np


def make_problem(n, dim=10, samples=40, seed=0, kind="logistic"):
    rng = np.random.RandomState(seed)
    w_star = rng.randn(dim, 1)
    A = rng.randn(n, samples, dim)
    if kind == "logistic":
        prob = 1.0 / (1.0 + np.exp(-A @ w_star))
        y = (rng.rand(n, samples, 1) < prob) * 2.0 - 1.0  # labels in {-1, 1}
    else:
        y = A @ w_star + 0.01 * rng.randn(n, samples, 1)
    return A.astype(np.float64), y.astype(np.float64), w_star


def logistic_grad(w, A, y, rho=1e-2):
    """Per-rank gradient of regularized logistic loss; w: (n, dim, 1)."""
    margins = y * (A @ w)                     # (n, s, 1)
    sig = 1.0 / (1.0 + np.exp(margins))
    g = -(A.transpose(0, 2, 1) @ (y * sig)) / A.shape[1]
    return g + rho * w


def global_minimizer(A, y, rho=1e-2, iters=4000, lr=0.5):
    """Centralized full-batch solution (the oracle all algorithms chase)."""
    n, s, dim = A.shape
    Af = A.reshape(n * s, dim)[None]
    yf = y.reshape(n * s, 1)[None]
    w = np.zeros((1, dim, 1))
    for _ in range(iters):
        w -= lr * logistic_grad(w, Af, yf, rho)
    return w[0]


def diffusion(bf, A, y, *, lr=0.5, iters=200, rho=1e-2):
    """ATC diffusion: x <- combine(x - lr * grad(x))."""
    n = A.shape[0]
    x = np.zeros((n, A.shape[2], 1))
    for _ in range(iters):
        half = x - lr * logistic_grad(x, A, y, rho)
        x = np.asarray(bf.neighbor_allreduce(half), dtype=np.float64)
    return x

def exact_diffusion(bf, A, y, *, lr=0.5, iters=600, rho=1e-2):
    """Exact diffusion (reference ``:175-246``): correction step removes the
    steady-state bias of plain diffusion.

        psi_k   = x_k - lr * grad(x_k)
        phi_k   = psi_k + x_k - psi_{k-1}
        x_{k+1} = combine_bar(phi_k)        # bar-W = (I + W)/2

    The half-averaged combine matrix keeps the recursion contractive (as in
    the exact-diffusion paper and the reference's example).
    """
    n = A.shape[0]
    x = np.zeros((n, A.shape[2], 1))
    psi_prev = x.copy()
    for k in range(iters):
        psi = x - lr * logistic_grad(x, A, y, rho)
        phi = psi + x - psi_prev if k > 0 else psi
        x = 0.5 * phi + 0.5 * np.asarray(bf.neighbor_allreduce(phi),
                                         dtype=np.float64)
        psi_prev = psi
    return x


def gradient_tracking(bf, A, y, *, lr=0.5, iters=1000, rho=1e-2):
    """DIGing (reference ``:249-361``): track the global gradient with an
    auxiliary variable communicated alongside the iterate.

        x_{k+1} = combine(x_k) - lr * q_k
        q_{k+1} = combine(q_k) + grad(x_{k+1}) - grad(x_k)
    """
    n = A.shape[0]
    x = np.zeros((n, A.shape[2], 1))
    g = logistic_grad(x, A, y, rho)
    q = g.copy()
    for _ in range(iters):
        x_new = np.asarray(bf.neighbor_allreduce(x),
                           dtype=np.float64) - lr * q
        g_new = logistic_grad(x_new, A, y, rho)
        q = np.asarray(bf.neighbor_allreduce(q), dtype=np.float64) \
            + g_new - g
        x, g = x_new, g_new
    return x


def push_diging(bf, A, y, *, lr=0.2, iters=1500, rho=1e-2):
    """Push-DIGing (reference ``:364-444``): gradient tracking on a DIRECTED
    graph using column-stochastic push weights + de-bias scalars, expressed
    with the window API (win_accumulate / win_update_then_collect)."""
    from bluefog_tpu import topology as topo_mod
    n = A.shape[0]
    dim = A.shape[2]
    topo = bf.load_topology()
    outs = [topo_mod.out_neighbor_ranks(topo, r) for r in range(n)]
    share = np.array([1.0 / (len(o) + 1.0) for o in outs])
    dstw = {(r, o): share[r] for r in range(n) for o in outs[r]}

    bf.turn_on_win_ops_with_associated_p()
    # One window carries cat(x, q) so both travel in a single push round.
    xq = np.zeros((n, 2 * dim, 1))
    g = logistic_grad(xq[:, :dim], A, y, rho)
    xq[:, dim:] = g
    bf.win_create(xq, "push_diging", zero_init=True)
    try:
        for _ in range(iters):
            z = xq[:, :dim]  # de-biased handled below
            xq = xq.copy()
            xq[:, :dim] = xq[:, :dim] - lr * xq[:, dim:]
            bf.win_accumulate(xq, "push_diging", self_weight=share,
                              dst_weights=dstw)
            xq = np.asarray(bf.win_update_then_collect("push_diging"),
                            dtype=np.float64)
            p = np.asarray(bf.win_associated_p("push_diging"))
            z_new = xq[:, :dim] / p[:, None, None]
            g_new = logistic_grad(z_new, A, y, rho)
            xq[:, dim:] += g_new - g
            g = g_new
        p = np.asarray(bf.win_associated_p("push_diging"))
        return xq[:, :dim] / p[:, None, None]
    finally:
        bf.win_free("push_diging")
        bf.turn_off_win_ops_with_associated_p()


ALGORITHMS = {
    "diffusion": diffusion,
    "exact_diffusion": exact_diffusion,
    "gradient_tracking": gradient_tracking,
    "push_diging": push_diging,
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--method", choices=list(ALGORITHMS) + ["all"],
                    default="all")
    ap.add_argument("--max-iters", type=int, default=None,
                    help="override each algorithm's tuned default")
    ap.add_argument("--lr", type=float, default=None)
    args = ap.parse_args()

    import bluefog_tpu as bf
    from bluefog_tpu import topology

    bf.init()
    n = bf.size()
    A, y, _ = make_problem(n)
    w_opt = global_minimizer(A, y)

    methods = list(ALGORITHMS) if args.method == "all" else [args.method]
    for name in methods:
        if name == "push_diging":
            bf.set_topology(topology.RingGraph(n, connect_style=2))
        else:
            bf.set_topology(topology.ExponentialTwoGraph(n))
        kw = {}
        if args.lr is not None:
            kw["lr"] = args.lr
        if args.max_iters is not None:
            kw["iters"] = args.max_iters
        x = ALGORITHMS[name](bf, A, y, **kw)
        err = np.linalg.norm(x - w_opt[None]) / max(
            np.linalg.norm(w_opt), 1e-12)
        print(f"{name:18s} relative error vs global minimizer: {err:.3e}")


if __name__ == "__main__":
    main()
