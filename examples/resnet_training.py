"""Full decentralized image-classification training: the reference's
``examples/pytorch_resnet.py`` protocol, TPU-native.

Covers the same pieces: per-rank data sharding, initial parameter broadcast,
the dist-optimizer grid (neighbor/hierarchical/allreduce/gradient/win_put),
ATC/AWC orders, dynamic one-peer topology, local aggregation
(``--batches-per-communication``), LR warmup + milestone decay
(arxiv 1706.02677 — here an *optax schedule on the update count*, so the
decay position survives checkpoint resume for free, unlike the reference's
manual ``adjust_learning_rate``), per-epoch validation accuracy, and
checkpoint save/resume (``utils/checkpoint.py`` replaces the reference's
``checkpoint-{epoch}.pth``).

Data is synthetic-but-learnable (class-conditional Gaussian images) so the
example runs anywhere the framework does — swap ``make_dataset`` for a real
input pipeline in production.

    python examples/resnet_training.py --model resnet18 --epochs 3
"""

import argparse
import time


def build_parser():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet18",
                    choices=["resnet18", "resnet34", "resnet50", "lenet",
                             "vit"])
    ap.add_argument("--image-size", type=int, default=32)
    ap.add_argument("--num-classes", type=int, default=10)
    ap.add_argument("--samples-per-rank", type=int, default=512)
    ap.add_argument("--val-samples", type=int, default=256)
    ap.add_argument("--batch-size", type=int, default=32,
                    help="per-rank batch size")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--base-lr", type=float, default=0.0125)
    ap.add_argument("--warmup-epochs", type=float, default=1)
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--dist-optimizer", default="neighbor_allreduce",
                    choices=["neighbor_allreduce", "allreduce",
                             "hierarchical", "gradient_allreduce", "win_put",
                             "empty"])
    ap.add_argument("--atc-style", action="store_true")
    ap.add_argument("--disable-dynamic-topology", action="store_true")
    ap.add_argument("--batches-per-communication", type=int, default=1,
                    help="local aggregation: communicate every J batches")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="save a checkpoint per epoch; resume if present")
    ap.add_argument("--seed", type=int, default=42)
    return ap


def make_dataset(n_ranks, per_rank, image, classes, seed, *,
                 pattern_seed=0):
    """Class-conditional Gaussians: class c has mean pattern_c; learnable by
    any conv net, rank-sharded like the reference's DistributedSampler.
    ``pattern_seed`` fixes the class means so train/val share a
    distribution while drawing independent samples via ``seed``."""
    import numpy as np
    patterns = np.random.RandomState(pattern_seed).randn(
        classes, image, image, 3).astype(np.float32)
    rng = np.random.RandomState(seed)
    y = rng.randint(0, classes, size=(n_ranks, per_rank))
    x = 0.35 * rng.randn(n_ranks, per_rank, image, image, 3) \
        .astype(np.float32) + patterns[y]
    return x, y


def lr_schedule(args, n, batches_per_epoch):
    """Warmup lr -> lr*size over warmup_epochs, then x0.1 at 2/3 and x0.01
    at 5/6 of training (the reference's 90-epoch milestones, scaled)."""
    import optax
    warm = max(1, int(args.warmup_epochs * batches_per_epoch))
    total = args.epochs * batches_per_epoch
    peak = args.base_lr * n
    # Distinct positive decay boundaries even for very short runs (a dict
    # with colliding keys would silently drop a decay decade).
    b1 = max(1, int(total * 2 / 3) - warm)
    b2 = max(b1 + 1, int(total * 5 / 6) - warm)
    return optax.join_schedules([
        optax.linear_schedule(args.base_lr, peak, warm),
        optax.piecewise_constant_schedule(peak, {b1: 0.1, b2: 0.1}),
    ], [warm])


def main():
    args = build_parser().parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import bluefog_tpu as bf
    from bluefog_tpu import models
    from bluefog_tpu.optim import CommunicationType
    from bluefog_tpu.utils import checkpoint

    bf.init(local_size=None if args.dist_optimizer != "hierarchical"
            else max(1, len(jax.devices()) // 2))
    n = bf.size()

    if args.model == "lenet":
        model = models.LeNet5(num_classes=args.num_classes)
        has_bn = False
    elif args.model == "vit":
        # Small ViT fit to the example's image size: the patch must DIVIDE
        # the image, so take the largest divisor at most size // 4
        # (worst case 1x1 patches — more tokens, still valid).
        patch = next(p for p in range(max(2, args.image_size // 4), 0, -1)
                     if args.image_size % p == 0)
        model = models.ViT(num_classes=args.num_classes,
                           image_size=args.image_size, patch_size=patch,
                           embed_dim=64, num_layers=4, num_heads=4,
                           dtype=jnp.float32)
        has_bn = False
    else:
        model = getattr(models, args.model.replace("resnet", "ResNet"))(
            num_classes=args.num_classes)
        has_bn = True

    x_train, y_train = make_dataset(n, args.samples_per_rank,
                                    args.image_size, args.num_classes,
                                    args.seed)
    x_val, y_val = make_dataset(n, max(1, args.val_samples // n),
                                args.image_size, args.num_classes,
                                args.seed + 1)
    x_val = x_val.reshape(-1, *x_val.shape[2:])
    y_val = y_val.reshape(-1)

    variables = model.init(jax.random.PRNGKey(args.seed),
                           jnp.asarray(x_train[0][:2]))
    rank_major = lambda t: jax.tree.map(
        lambda a: jnp.broadcast_to(a[None], (n,) + a.shape), t)
    params = rank_major(variables["params"])
    bstats = rank_major(variables["batch_stats"]) if has_bn else None
    # Reference: bf.broadcast_parameters(model.state_dict(), root_rank=0)
    params = bf.broadcast_parameters(params, root_rank=0)

    batches_per_epoch = args.samples_per_rank // args.batch_size
    if batches_per_epoch < 1:
        raise SystemExit(
            f"--batch-size {args.batch_size} exceeds --samples-per-rank "
            f"{args.samples_per_rank}: no full batch per epoch")
    base = optax.sgd(lr_schedule(args, n, batches_per_epoch),
                     momentum=args.momentum)

    comm = {"neighbor_allreduce": CommunicationType.neighbor_allreduce,
            "allreduce": CommunicationType.allreduce,
            "hierarchical": CommunicationType.hierarchical_neighbor_allreduce,
            "empty": CommunicationType.empty}.get(args.dist_optimizer)
    if args.dist_optimizer == "gradient_allreduce":
        opt = bf.optim.DistributedGradientAllreduceOptimizer(
            base, num_steps_per_communication=args.batches_per_communication)
    elif args.dist_optimizer == "win_put":
        opt = bf.optim.DistributedWinPutOptimizer(
            base, num_steps_per_communication=args.batches_per_communication)
    else:
        cls = (bf.optim.DistributedAdaptThenCombineOptimizer if args.atc_style
               else bf.optim.DistributedAdaptWithCombineOptimizer)
        opt = cls(base, comm,
                  use_dynamic_topology=not args.disable_dynamic_topology,
                  num_steps_per_communication=args.batches_per_communication)
    state = opt.init(params)

    if has_bn:
        def loss_fn(p, bs, xb, yb):
            logits, new = model.apply(
                {"params": p, "batch_stats": bs}, xb, train=True,
                mutable=["batch_stats"])
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, yb).mean()
            return loss, new["batch_stats"]
        vgrad = jax.jit(jax.vmap(jax.value_and_grad(loss_fn, has_aux=True)))

        @jax.jit
        def infer(p, bs, xb):
            return model.apply({"params": p, "batch_stats": bs}, xb,
                               train=False)
    else:
        def loss_fn(p, xb, yb):
            logits = model.apply({"params": p}, xb)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yb).mean(), jnp.zeros(())
        vgrad = jax.jit(jax.vmap(jax.value_and_grad(loss_fn, has_aux=True)))

        @jax.jit
        def infer(p, _, xb):
            return model.apply({"params": p}, xb)

    start_epoch = 0
    if args.checkpoint_dir:
        latest = checkpoint.latest_step(args.checkpoint_dir)
        if latest is not None:
            tmpl = {"params": params, "state": state,
                    **({"bstats": bstats} if has_bn else {}),
                    "epoch": np.zeros((), np.int32)}
            back = checkpoint.restore(args.checkpoint_dir, step=latest,
                                      target=tmpl)
            params = jax.tree.map(jnp.asarray, back["params"])
            state = jax.tree.map(jnp.asarray, back["state"])
            if has_bn:
                bstats = jax.tree.map(jnp.asarray, back["bstats"])
            start_epoch = int(back["epoch"]) + 1
            print(f"resumed from epoch {start_epoch - 1}")

    def validate(params, bstats):
        p0 = jax.tree.map(lambda a: a[0], params)
        bs0 = jax.tree.map(lambda a: a[0], bstats) if has_bn else None
        logits = infer(p0, bs0, jnp.asarray(x_val))
        return float((np.asarray(logits).argmax(-1) == y_val).mean())

    rng = np.random.RandomState(args.seed)
    # A fully-finished checkpoint still reports the restored model's quality.
    acc = validate(params, bstats) if start_epoch >= args.epochs else None
    for epoch in range(start_epoch, args.epochs):
        order = rng.permutation(args.samples_per_rank)
        t0 = time.time()
        running = 0.0
        for b in range(batches_per_epoch):
            idx = order[b * args.batch_size:(b + 1) * args.batch_size]
            xb = jnp.asarray(x_train[:, idx])
            yb = jnp.asarray(y_train[:, idx])
            if has_bn:
                (loss, bstats), grads = vgrad(params, bstats, xb, yb)
            else:
                (loss, _), grads = vgrad(params, xb, yb)
            params, state = opt.step(params, grads, state)
            running += float(loss.mean())
        acc = validate(params, bstats)
        print(f"epoch {epoch}: loss {running / batches_per_epoch:.4f} "
              f"val_acc {acc:.3f} ({time.time() - t0:.1f}s)")
        if args.checkpoint_dir:
            checkpoint.save(
                args.checkpoint_dir,
                {"params": params, "state": state,
                 **({"bstats": bstats} if has_bn else {}),
                 "epoch": np.asarray(epoch, np.int32)}, step=epoch)
    print(f"final val_acc {acc:.3f}")


if __name__ == "__main__":
    main()
