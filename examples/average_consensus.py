"""Average consensus — the smallest end-to-end slice (BASELINE config 1).

Parity: reference ``examples/pytorch_average_consensus.py``: every rank holds
a random vector; repeated neighbor averaging (static ring or dynamic one-peer
Exp2) drives all ranks to the global mean.

Run on a virtual 8-rank CPU mesh:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/average_consensus.py
or on real TPU devices: python examples/average_consensus.py
"""

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=1000)
    ap.add_argument("--max-iters", type=int, default=200)
    ap.add_argument("--dynamic", action="store_true",
                    help="one-peer dynamic Exp2 instead of static ring")
    args = ap.parse_args()

    import bluefog_tpu as bf
    from bluefog_tpu import topology

    bf.init()
    n = bf.size()
    if not args.dynamic:
        bf.set_topology(topology.RingGraph(n), is_weighted=True)
    x = np.random.randn(n, args.dim).astype(np.float32)
    target = x.mean(axis=0)

    for t in range(args.max_iters):
        if args.dynamic:
            x = np.asarray(bf.dynamic_neighbor_allreduce(x, t))
        else:
            x = np.asarray(bf.neighbor_allreduce(x))
        err = np.abs(x - target).max()
        if t % 20 == 0 or err < 1e-6:
            print(f"iter {t:4d}  max consensus error {err:.3e}")
        if err < 1e-6:
            break
    assert err < 1e-4, f"consensus failed: {err}"
    print(f"consensus reached in {t + 1} iterations "
          f"({'dynamic exp2' if args.dynamic else 'static ring'}, "
          f"{n} ranks)")


if __name__ == "__main__":
    main()
