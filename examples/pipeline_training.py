"""Pipeline-parallel training: a deep net split into one stage per device.

Beyond the reference (data-parallel only, SURVEY §2.3).  Each device holds
ONE layer of an n-layer tanh MLP; GPipe microbatches stream through the
``parallel.pipeline_apply`` schedule (one ``lax.scan`` of
``M + n - 1`` ticks, stage handoff = one ``ppermute`` hop per tick) and
reverse-mode AD flows straight through it — no hand-written backward
schedule.  The example trains a regression, checks the pipelined forward
against running the layers sequentially, and asserts the loss fell.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/pipeline_training.py

(The library also ships ``parallel.pipeline_train_step_interleaved`` —
Megatron virtual-stage chunks with an O(n/(vM)) bubble; see the oracle in
``tests/test_parallel.py::test_interleaved_1f1b_matches_sequential_grads``.)
"""

import argparse


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--lr", type=float, default=1e-2)
    ap.add_argument("--microbatches", type=int, default=8)
    ap.add_argument("--microbatch-size", type=int, default=16)
    ap.add_argument("--width", type=int, default=32)
    ap.add_argument("--schedule", choices=["gpipe", "1f1b", "zb"],
                    default="gpipe",
                    help="gpipe: AD through pipeline_apply (O(M) "
                         "residuals); 1f1b: in-scan manual VJP "
                         "(O(n) per-stage residency)")
    args = ap.parse_args()
    if args.steps < 2:
        ap.error("--steps must be >= 2 (the run asserts the loss fell)")

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from bluefog_tpu.parallel import pipeline_apply, pipeline_train_step

    devs = jax.devices()
    n = len(devs)  # one pipeline stage per device
    M, mb, d = args.microbatches, args.microbatch_size, args.width
    mesh = Mesh(np.asarray(devs), ("pp",))

    rng = np.random.RandomState(0)
    # Stage i's parameters: stacked (n, d, d) weights + (n, d) biases,
    # sharded P("pp") so each device holds exactly its own layer.
    Ws = jnp.asarray(rng.randn(n, d, d) * (1.0 / np.sqrt(d)), jnp.float32)
    bs = jnp.zeros((n, d), jnp.float32)
    x = jnp.asarray(rng.randn(M, mb, d), jnp.float32)
    w_true = jnp.asarray(rng.randn(d, d) * 0.3, jnp.float32)
    y = jnp.tanh(x @ w_true)  # learnable target

    def stage_fn(p, xb):
        W, b = p
        return jnp.tanh(xb @ W[0] + b[0])

    def pp_forward(params, x):
        return jax.shard_map(
            lambda p, xb: pipeline_apply(stage_fn, p, xb, axis_name="pp"),
            mesh=mesh, in_specs=((P("pp"), P("pp")), P()), out_specs=P(),
            check_vma=False)(params, x)

    def loss_fn(params):
        return jnp.mean((pp_forward(params, x) - y) ** 2)

    opt = optax.adam(args.lr)
    params = (jax.device_put(Ws, NamedSharding(mesh, P("pp"))),
              jax.device_put(bs, NamedSharding(mesh, P("pp"))))
    state = opt.init(params)

    if args.schedule in ("1f1b", "zb"):
        def mb_loss(out, tb):
            return jnp.mean((out - tb) ** 2)

        # "zb" = ZB-H1 split backward: input-grad on the B tick, deferred
        # weight-grad filling forward/idle ticks (same gradients).
        onef1b = jax.shard_map(
            lambda p, xb, tb: pipeline_train_step(
                stage_fn, p, xb, tb, mb_loss, axis_name="pp",
                split_backward=(args.schedule == "zb")),
            mesh=mesh, in_specs=((P("pp"), P("pp")), P(), P()),
            out_specs=(P(), (P("pp"), P("pp"))), check_vma=False)

        @jax.jit
        def _step_1f1b(p, s):
            l, g = onef1b(p, x, y)
            up, s = opt.update(g, s, p)
            return optax.apply_updates(p, up), s, l

        # AOT-compile ONCE: the executable serves both the memory report
        # and the training loop (a separate jit call would recompile the
        # whole 2M+2n-2-tick scan).
        step = _step_1f1b.lower(params, state).compile()
        mem = step.memory_analysis()
        if mem is not None:
            print(f"1f1b compiled temp memory: {mem.temp_size_in_bytes} "
                  "bytes (O(n) stash; GPipe-through-AD holds O(M) scan "
                  "residuals)")
    else:
        @jax.jit
        def step(p, s):
            l, g = jax.value_and_grad(loss_fn)(p)
            up, s = opt.update(g, s, p)
            return optax.apply_updates(p, up), s, l

    l0 = None
    for i in range(args.steps):
        params, state, loss = step(params, state)
        if (i + 1) % 8 == 0:
            # Bound async-dispatch depth: the XLA CPU runtime aborts when
            # too many collective-bearing programs queue unsynced (the
            # scan+ppermute schedule is exactly that shape).
            jax.block_until_ready(loss)
        if i == 0:
            l0 = float(loss)
        if (i + 1) % 50 == 0:
            print(f"step {i + 1}  loss {float(loss):.5f} "
                  f"({n} stages x {M} microbatches)")
    lf = float(loss)

    # correctness: the pipelined forward equals running layers sequentially
    # (reference computed on-device too, so both use the backend's native
    # matmul precision — TPU matmuls are bf16 by default)
    Wd, bd = params
    ref = x
    for i in range(n):
        ref = jnp.tanh(ref @ Wd[i] + bd[i])
    got = np.asarray(pp_forward(params, x))
    np.testing.assert_allclose(got, np.asarray(ref), rtol=1e-4, atol=1e-5)

    assert lf < l0, (l0, lf)
    print(f"done: loss {l0:.5f} -> {lf:.5f}; pipelined forward matches the "
          f"sequential stack (schedule depth {M + n - 1} ticks)")


if __name__ == "__main__":
    main()
