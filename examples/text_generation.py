"""Train a tiny character LM and generate text with the KV cache.

No reference counterpart (BlueFog predates LLM workloads).  Demonstrates
the inference path: a Llama-style TransformerLM (GQA + RoPE + SwiGLU)
memorizes a pangram, then ``models.transformer.generate`` continues a
prompt through one batched prefill + a fused single-token decode scan —
the KV cache stores the shared kv heads, so GQA shrinks it 4x here.

    python examples/text_generation.py
    python examples/text_generation.py --temperature 0.8   # sampled
"""

import argparse

TEXT = ("the quick brown fox jumps over the lazy dog. "
        "pack my box with five dozen liquor jugs. ")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--prompt", default="the quick brown ")
    ap.add_argument("--max-new-tokens", type=int, default=48)
    ap.add_argument("--temperature", type=float, default=0.0)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from bluefog_tpu.models import TransformerLM, TransformerConfig
    from bluefog_tpu.models.transformer import generate

    vocab = sorted(set(TEXT))
    stoi = {c: i for i, c in enumerate(vocab)}
    unknown = [c for c in args.prompt if c not in stoi]
    if unknown:  # fail before the expensive training loop
        raise SystemExit(f"prompt contains unseen characters: {unknown}")
    data = jnp.asarray([stoi[c] for c in TEXT * 4])[None, :]

    cfg = TransformerConfig(
        vocab_size=len(vocab), num_layers=2, num_heads=8, num_kv_heads=2,
        embed_dim=128, max_seq_len=int(data.shape[1]),
        pos_encoding="rope", mlp="swiglu", dtype=jnp.float32)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0), data[:, :8])
    opt = optax.adam(args.lr)
    state = opt.init(params)

    @jax.jit
    def step(p, s):
        def loss(p):
            logits = model.apply(p, data[:, :-1])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, data[:, 1:]).mean()
        l, g = jax.value_and_grad(loss)(p)
        up, s = opt.update(g, s, p)
        return optax.apply_updates(p, up), s, l

    for i in range(args.steps):
        params, state, loss = step(params, state)
        if (i + 1) % 100 == 0:
            print(f"step {i + 1}  loss {float(loss):.4f}")

    prompt = jnp.asarray([stoi[c] for c in args.prompt])[None, :]
    out = generate(model, params, prompt, args.max_new_tokens,
                   temperature=args.temperature,
                   rng=jax.random.PRNGKey(0))
    text = "".join(vocab[int(t)] for t in np.asarray(out[0]))
    print(f"prompt:    {args.prompt!r}")
    print(f"generated: {text!r}")
    if args.temperature == 0.0 and TEXT.startswith(args.prompt):
        # Exact-match is only guaranteed for training-PREFIX prompts: a
        # mid-text prompt starts generation from a zero-context boundary
        # the model never trained on, so its first tokens drift.
        need = len(args.prompt) + args.max_new_tokens
        want = (TEXT * (need // len(TEXT) + 2))[len(args.prompt):need]
        assert text == want, (text, want)
        print("greedy continuation matches the training text exactly")


if __name__ == "__main__":
    main()
