"""MNIST LeNet with DistributedNeighborAllreduceOptimizer (BASELINE config 2).

Parity: reference ``examples/pytorch_mnist.py``.  The sandbox has no dataset
downloads (zero egress), so a synthetic MNIST stand-in is generated: each
class is a fixed random 28x28 prototype plus noise — linearly separable enough
that accuracy cleanly tracks optimization progress, while every rank trains on
its own disjoint shard (the decentralized-DP setting).
"""

import argparse

import numpy as np


def synthetic_mnist(n_ranks, per_rank, seed=0, proto_seed=42):
    """Class prototypes are fixed by ``proto_seed`` (the task definition);
    ``seed`` only drives the sampled labels/noise so train and held-out sets
    share the same underlying task."""
    prototypes = np.random.RandomState(proto_seed).randn(
        10, 28, 28, 1).astype(np.float32)
    rng = np.random.RandomState(seed)
    ys = rng.randint(0, 10, size=(n_ranks, per_rank))
    xs = prototypes[ys] + 0.8 * rng.randn(
        n_ranks, per_rank, 28, 28, 1).astype(np.float32)
    return xs, ys


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--per-rank-samples", type=int, default=512)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--base-optimizer", choices=["adam", "sgd"],
                    default="adam")
    ap.add_argument("--dist-optimizer",
                    choices=["neighbor_allreduce", "allreduce",
                             "gradient_allreduce", "empty"],
                    default="neighbor_allreduce")
    ap.add_argument("--dynamic", action="store_true",
                    help="one-peer dynamic Exp2 topology")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    import bluefog_tpu as bf
    from bluefog_tpu.models import LeNet5
    from bluefog_tpu.optim import CommunicationType

    bf.init()
    n = bf.size()
    xs, ys = synthetic_mnist(n, args.per_rank_samples)
    xt, yt = synthetic_mnist(n, 256, seed=123)  # held-out

    model = LeNet5()
    params0 = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1)))
    params = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), params0)

    base = (optax.adam(args.lr) if args.base_optimizer == "adam"
            else optax.sgd(args.lr, momentum=0.9))
    if args.dist_optimizer == "gradient_allreduce":
        opt = bf.optim.DistributedGradientAllreduceOptimizer(base)
    else:
        opt = bf.optim.DistributedAdaptWithCombineOptimizer(
            base,
            CommunicationType(args.dist_optimizer.replace(
                "neighbor_allreduce", "neighbor.allreduce")),
            use_dynamic_topology=args.dynamic)
    state = opt.init(params)

    def loss_fn(p, x, y):
        logits = model.apply(p, x)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    grad_all = jax.jit(jax.vmap(jax.grad(loss_fn)))

    @jax.jit
    def accuracy(params, x, y):
        logits = jax.vmap(model.apply)(params, x)
        return (logits.argmax(-1) == y).mean()

    # Framework input pipeline: rank-partitioned sampling + host-async
    # device prefetch (the reference's DistributedSampler+DataLoader role,
    # ``examples/pytorch_mnist.py:100-120``).  static_shards keeps each
    # rank's data fixed across epochs — the heterogeneous decentralized-DP
    # setting this example demonstrates (shuffling happens within shards).
    loader = bf.data.ShardedLoader(
        {"x": xs.reshape(-1, 28, 28, 1), "y": ys.reshape(-1)},
        batch_size=args.batch_size, seed=1, static_shards=True)
    for epoch in range(args.epochs):
        loader.set_epoch(epoch)
        for batch in loader:
            grads = grad_all(params, batch["x"], batch["y"])
            params, state = opt.step(params, grads, state)
        acc = float(accuracy(params, jnp.asarray(xt), jnp.asarray(yt)))
        print(f"epoch {epoch}  held-out accuracy {acc:.4f}")
    assert acc > 0.9, f"training failed: accuracy {acc}"
    print(f"final accuracy {acc:.4f} "
          f"({args.dist_optimizer}, {n} ranks, "
          f"{'dynamic' if args.dynamic else 'static'} topology)")


if __name__ == "__main__":
    main()
