"""Tensor-parallel (+ data-parallel) LM training via GSPMD layouts.

Beyond the reference (data-parallel only, SURVEY §2.3).  Megatron-style
tensor parallelism here is a LAYOUT, not an algorithm:
``parallel.tp_param_specs`` marks each big matmul column- or row-parallel
over the "tp" mesh axis, ``tp_shard_params`` places the weights, and XLA's
GSPMD partitioner inserts the psums — the training step is the ordinary
single-device code under one ``jit``.

    # 2-way data x 4-way tensor parallel on 8 virtual devices
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/tensor_parallel_training.py
"""

import argparse


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=150)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--tp", type=int, default=4, help="tensor-parallel ways")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=64)
    args = ap.parse_args()
    if args.steps < 2:
        ap.error("--steps must be >= 2 (the run asserts the loss fell)")

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from bluefog_tpu import models
    from bluefog_tpu.parallel import tp_shard_params

    devs = jax.devices()
    n = len(devs)
    tp = args.tp
    if tp < 1 or n % tp != 0:
        raise SystemExit(f"--tp {tp} must divide the {n} devices")
    dp = n // tp
    mesh = Mesh(np.asarray(devs).reshape(dp, tp), ("dp", "tp"))

    cfg = models.TransformerConfig(
        vocab_size=256, num_layers=2, num_heads=8, embed_dim=128,
        max_seq_len=args.seq_len, dtype=jnp.float32, mlp="swiglu")
    model = models.TransformerLM(cfg)

    # Same learnable synthetic language as the long-context example.
    rng = np.random.RandomState(0)
    toks = np.zeros((args.batch, args.seq_len + 1), np.int32)
    for b in range(args.batch):
        for i in range(args.seq_len):
            toks[b, i + 1] = (toks[b, i] * 5 + 3) % 256 \
                if rng.rand() > 0.05 else rng.randint(256)
    tokens = jnp.asarray(toks[:, :-1])
    targets = jnp.asarray(toks[:, 1:])

    params = model.init(jax.random.PRNGKey(0), tokens[:, :8])
    # THE tensor-parallel step: place params per the Megatron layout and
    # shard the batch over dp.  Nothing else changes.
    params = tp_shard_params(params, mesh)
    tokens = jax.device_put(tokens, NamedSharding(mesh, P("dp")))
    targets = jax.device_put(targets, NamedSharding(mesh, P("dp")))

    opt = optax.adam(args.lr)
    state = opt.init(params)

    def loss_fn(p):
        logits = model.apply(p, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()

    @jax.jit
    def step(p, s):
        l, g = jax.value_and_grad(loss_fn)(p)
        up, s = opt.update(g, s, p)
        return optax.apply_updates(p, up), s, l

    l0 = None
    for i in range(args.steps):
        params, state, loss = step(params, state)
        if (i + 1) % 8 == 0:
            jax.block_until_ready(loss)  # bound CPU-mesh dispatch depth
        if i == 0:
            l0 = float(loss)
        if (i + 1) % 50 == 0:
            print(f"step {i + 1}  loss {float(loss):.4f} "
                  f"({dp}-way data x {tp}-way tensor parallel)")
    lf = float(loss)
    assert lf < l0, (l0, lf)

    # show the layout actually took: a qkv kernel is column-sharded over tp
    # (a size-1 tp axis canonicalizes to a replicated spec — nothing to cut)
    qkv = params["params"]["block_0"]["qkv"]["kernel"]
    if tp > 1:
        assert "tp" in str(qkv.sharding.spec), qkv.sharding
    print(f"done: loss {l0:.4f} -> {lf:.4f}; qkv kernel sharding "
          f"{qkv.sharding.spec} over mesh {dict(mesh.shape)}")


if __name__ == "__main__":
    main()
