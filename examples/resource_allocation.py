"""Decentralized optimal exchange (resource allocation).

Parity: reference ``examples/resource_allocation.ipynb`` — a network of n
nodes solves

    min_{x_i}  sum_i 1/2 ||A_i x_i - b_i||^2   s.t.  sum_i x_i = 0,

the classic market-exchange / resource-allocation problem.  The coupling
constraint is handled two ways, exactly as the notebook teaches:

* **distributed ADMM** — primal x-updates are local closed-form solves; the
  coupling residual mean rides ``bf.allreduce`` each iteration.
* **dual decentralized methods** — the dual problem is an unconstrained
  consensus optimization over the price vector y (KKT: every node faces one
  price), so EXTRA, exact diffusion, and gradient tracking run on y with
  ``bf.neighbor_allreduce``; each node recovers its allocation
  x_i(y) = (A_i^T A_i)^(-1) (A_i^T b_i - y).

Everything is rank-major numpy over the framework's eager ops — run it on
the virtual CPU mesh or a real TPU mesh unchanged.

    python examples/resource_allocation.py --method extra
"""

import argparse

import numpy as np


def make_problem(n, m=10, d=5, seed=7):
    """Per-rank least squares pieces; H_i = A_i^T A_i invertible (m > d)."""
    rng = np.random.RandomState(seed)
    A = rng.rand(n, m, d)
    b = rng.rand(n, m, 1)
    Hinv = np.stack([np.linalg.inv(A[i].T @ A[i]) for i in range(n)])
    ATb = np.einsum("nmd,nmo->ndo", A, b)
    return A, b, Hinv, ATb


def kkt_solution(Hinv, ATb):
    """Closed form from the KKT system: x_i = Hinv_i (ATb_i - y*), with the
    price y* chosen so allocations clear: sum_i x_i = 0."""
    S = np.linalg.inv(Hinv.sum(0))
    y_star = S @ np.einsum("ndk,nko->ndo", Hinv, ATb).sum(0)
    x_star = np.einsum("ndk,nko->ndo", Hinv, ATb - y_star[None])
    return x_star, y_star


def allocations(y, Hinv, ATb):
    """x_i(y_i): each node's best response to its local price estimate."""
    return np.einsum("ndk,nko->ndo", Hinv, ATb - y)


def rel_error(bf, x, x_star):
    """Network-averaged relative allocation error (the notebook's metric)."""
    dist = np.sum((x - x_star) ** 2, axis=(1, 2)) / np.sum(x_star ** 2)
    return float(np.sqrt(np.asarray(
        bf.allreduce(dist[:, None], average=True)).mean()))


def admm(bf, A, b, Hinv, ATb, x_star, *, rho=1.0, iters=300):
    n, m, d = A.shape
    IpATA_inv = np.stack([
        np.linalg.inv(rho * np.eye(d) + A[i].T @ A[i]) for i in range(n)])
    x = np.zeros((n, d, 1))
    u = np.zeros((n, d, 1))
    errs = []
    for _ in range(iters):
        x = np.einsum("ndk,nko->ndo", IpATA_inv,
                      ATb + rho * (x - _mean(bf, x) - u))
        x_bar = _mean(bf, x)
        u = u + x_bar
        errs.append(rel_error(bf, x, x_star))
    return errs


def _mean(bf, x):
    return np.asarray(bf.allreduce(x, average=True), dtype=np.float64)


def _nbr(bf, x):
    return np.asarray(bf.neighbor_allreduce(x), dtype=np.float64)


def _record(bf, errs, t, iters, x, x_star, every=100):
    """The error metric is itself an allreduce — sample it sparsely instead
    of doubling the collectives of 3000-iteration loops."""
    if t % every == 0 or t == iters - 1:
        errs.append(rel_error(bf, x, x_star))


def extra(bf, Hinv, ATb, x_star, *, lr=0.02, iters=3000):
    """EXTRA on the dual: y <- W(y - lr g) + correction (uses the previous
    combine to cancel the consensus bias)."""
    n, d = Hinv.shape[0], Hinv.shape[1]
    y = np.zeros((n, d, 1))
    y_prev = np.zeros((n, d, 1))
    g_prev = np.zeros((n, d, 1))
    errs = []
    for t in range(iters):
        g = -allocations(y, Hinv, ATb)      # dual gradient = -x(y)
        if t == 0:
            y_next = _nbr(bf, y - lr * g)
        else:
            y_next = _nbr(bf, 2 * y - y_prev - lr * (g - g_prev))
        y_prev, g_prev, y = y, g, y_next
        _record(bf, errs, t, iters, allocations(y, Hinv, ATb), x_star)
    return errs


def exact_diffusion(bf, Hinv, ATb, x_star, *, lr=0.02, iters=3000):
    n, d = Hinv.shape[0], Hinv.shape[1]
    y = np.zeros((n, d, 1))
    psi_prev = y.copy()  # psi_{-1} := y_0 makes the first correction vanish
    errs = []
    for t in range(iters):
        g = -allocations(y, Hinv, ATb)
        psi = y - lr * g
        y = _nbr(bf, psi + y - psi_prev)
        psi_prev = psi
        _record(bf, errs, t, iters, allocations(y, Hinv, ATb), x_star)
    return errs


def gradient_tracking(bf, Hinv, ATb, x_star, *, lr=0.02, iters=3000):
    n, d = Hinv.shape[0], Hinv.shape[1]
    y = np.zeros((n, d, 1))
    g_prev = -allocations(y, Hinv, ATb)
    z = g_prev.copy()                        # tracks the average gradient
    errs = []
    for t in range(iters):
        y = _nbr(bf, y - lr * z)
        g = -allocations(y, Hinv, ATb)
        z = _nbr(bf, z + g - g_prev)
        g_prev = g
        _record(bf, errs, t, iters, allocations(y, Hinv, ATb), x_star)
    return errs


METHODS = {"admm": admm, "extra": extra, "exact_diffusion": exact_diffusion,
           "gradient_tracking": gradient_tracking}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--method", default="extra", choices=sorted(METHODS))
    ap.add_argument("--iters", type=int, default=None)
    ap.add_argument("--lr", type=float, default=None)
    args = ap.parse_args()

    import bluefog_tpu as bf
    from bluefog_tpu import topology as topology_util

    bf.init()
    n = bf.size()
    # The notebook's half-weight combine (its GetRecvWeights lesson,
    # cells 14/17): EXTRA / exact diffusion need W~ = (I + W)/2 — strictly
    # diagonally-weighted symmetric doubly-stochastic — or they diverge.
    G = topology_util.SymmetricExponentialGraph(n)
    W = topology_util.weight_matrix(G)
    W_half = (np.eye(n) + W) / 2
    bf.set_topology(topology_util.from_weight_matrix(W_half),
                    is_weighted=True)

    A, b, Hinv, ATb = make_problem(n)
    x_star, y_star = kkt_solution(Hinv, ATb)
    assert np.abs(x_star.sum(0)).max() < 1e-8  # market clears

    kwargs = {}
    if args.iters is not None:
        kwargs["iters"] = args.iters
    if args.lr is not None and args.method != "admm":
        kwargs["lr"] = args.lr
    fn = METHODS[args.method]
    errs = (fn(bf, A, b, Hinv, ATb, x_star, **kwargs) if args.method == "admm"
            else fn(bf, Hinv, ATb, x_star, **kwargs))
    iters_run = kwargs.get("iters", 300 if args.method == "admm" else 3000)
    print(f"{args.method}: relative allocation error after "
          f"{iters_run} iters = {errs[-1]:.3e}")


if __name__ == "__main__":
    main()
