"""Mixture-of-experts training over ep x dp: switch-routed experts sharded
across an expert-parallel axis, composed with DECENTRALIZED data parallelism
in one shard_map program.

Beyond the reference (data-parallel only, SURVEY §2.3).  Each dp rank owns
its own replica of the router and trains on its own data shard; the expert
bank is sharded one-expert-per-rank over the ep axis (``parallel.moe_apply``,
Switch top-1 routing with static capacity); after the local step the
replicas gossip over the dp axis with the framework's decentralized combine
(static neighbor averaging by default, plain allreduce with
``--combine allreduce``).  The training objective includes the Switch
load-balancing auxiliary loss (``parallel.load_balance_loss``) — without it
the router collapses onto one expert and capacity drops become the only
regularizer.

Gradient conventions (pinned by
``tests/test_parallel.py::test_moe_composes_with_decentralized_dp``):
the per-rank objective is the global loss divided by ``ep`` (the psum
transpose otherwise inflates every gradient by the axis size), expert grads
are rank-local, and replicated-router grads are psum'd over ep.

    # 2-way decentralized dp x 4 experts on 8 virtual devices
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/moe_training.py
"""

import argparse


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--lr", type=float, default=0.3)
    ap.add_argument("--experts", type=int, default=4,
                    help="expert-parallel ways (ep axis size)")
    ap.add_argument("--tokens", type=int, default=64, help="tokens per rank")
    ap.add_argument("--dim", type=int, default=16)
    ap.add_argument("--aux-weight", type=float, default=0.01)
    ap.add_argument("--combine", choices=["neighbor", "allreduce"],
                    default="neighbor")
    args = ap.parse_args()
    if args.steps < 2:
        ap.error("--steps must be >= 2 (the run asserts the loss fell)")

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    from bluefog_tpu import topology as topo
    from bluefog_tpu.ops import collective as C
    from bluefog_tpu.ops import schedule as S
    from bluefog_tpu.parallel import load_balance_loss, moe_apply

    devs = jax.devices()
    n = len(devs)
    E = args.experts
    if E < 2 or n % E != 0:
        raise SystemExit(f"--experts {E} must be >= 2 and divide {n}")
    dp = n // E
    mesh = Mesh(np.asarray(devs).reshape(dp, E), ("dp", "ep"))
    T, d = args.tokens, args.dim

    rng = np.random.RandomState(0)
    # Piecewise-linear target: a hidden LINEAR gating matrix decides which
    # teacher map serves each token, so the (linear) router can represent
    # the true routing rule and the task rewards learning it.
    teachers = rng.randn(E, d, d).astype(np.float32)
    gating = rng.randn(d, E).astype(np.float32)

    def make_batch(seed):
        r = np.random.RandomState(seed)
        x = r.randn(dp, T, d).astype(np.float32)
        region = (x @ gating).argmax(-1)                   # (dp, T)
        t = np.einsum("ptd,ptde->pte", x, teachers[region])
        return jnp.asarray(x), jnp.asarray(t.astype(np.float32))

    params = {
        "experts": jnp.asarray(
            rng.randn(dp, E, d, d).astype(np.float32) * 0.3),
        "router": jnp.asarray(
            rng.randn(dp, d, E).astype(np.float32) * 0.3),
    }

    if args.combine == "allreduce":
        def combine(a):
            return C.allreduce(a, "dp", average=True)
    else:
        sched = S.compile_static(topo.RingGraph(dp),
                                 use_topo_weights=False) if dp > 1 else None

        def combine(a):
            return C.neighbor_allreduce(a, sched, "dp") if dp > 1 else a

    lr, auxw = args.lr, args.aux_weight

    def body(p, x, t):
        def loss_fn(p):
            lg = x[0] @ p["router"][0]
            # Linear experts: each can represent one teacher map exactly,
            # so task-loss progress measures routing + expert learning.
            y, aux = moe_apply(
                lambda w, z: z @ w[0, 0],
                p["experts"], x[0], lg, axis_name="ep", with_aux=True)
            task = jnp.mean((y - t[0]) ** 2)
            return (task + auxw * aux) / lax.axis_size("ep"), (task, aux)

        (_, (task, aux)), g = jax.value_and_grad(
            loss_fn, has_aux=True)(p)
        g["router"] = lax.psum(g["router"], "ep")  # replicated over ep
        p = jax.tree.map(lambda a, b: a - lr * b, p, g)
        p = jax.tree.map(combine, p)
        return p, task[None], aux[None]

    step = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=({"experts": P("dp", "ep"), "router": P("dp")},
                  P("dp"), P("dp")),
        out_specs=({"experts": P("dp", "ep"), "router": P("dp")},
                   P("dp"), P("dp")),
        check_vma=False))

    first = last = None
    for s in range(args.steps):
        x, t = make_batch(100 + s)
        params, task, aux = step(params, x, t)
        if s == 0:
            first = float(task.mean())
        last = float(task.mean())
        if s % 25 == 0 or s == args.steps - 1:
            print(f"step {s:4d}  task {task.mean():.4f}  "
                  f"aux {aux.mean():.4f}")

    assert np.isfinite(last), "diverged"
    assert last < first, f"no progress: {first:.4f} -> {last:.4f}"
    spread = float(np.abs(np.asarray(params["router"])
                          - np.asarray(params["router"]).mean(0)).max())
    print(f"final task loss {last:.4f} (from {first:.4f}); "
          f"router replica spread {spread:.4f}")
    print("MOE-TRAINING-OK")


if __name__ == "__main__":
    main()
