"""Benchmark the one-sided (window) gossip family — DP-7/8/9's data plane.

The collective family's numbers live in ``examples/benchmark.py``; this
measures the host-side window store and DCN transport that back
``win_put`` / ``win_accumulate`` / ``win_update`` and the async optimizers
(reference counterpart: chunked RMA, ``mpi_controller.cc:953-1184``).

Reported:
  * per-op wall time and MB/s for a fused ResNet-50-sized buffer
    (``win_put`` all-edges, ``win_accumulate``, ``win_update``,
    ``win_update_then_collect``)
  * dispatch latency of the nonblocking ops (the overlap window: how much
    compute can hide behind an in-flight put)
  * device<->host staging cost (the only part that touches the chip)
  * DP-7 (``DistributedWinPutOptimizer``) step rate vs the synchronous
    DP-3 (``DistributedNeighborAllreduceOptimizer``) on the same model
  * with ``--multiproc``, relaunches itself under ``bfrun -np 2`` and
    measures cross-process puts/s and bytes/s per DCN edge, with and
    without bf16 wire compression

Usage:
  python examples/window_benchmark.py [--elements N] [--rounds R]
  python examples/window_benchmark.py --multiproc
"""

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np


def _timeit(fn, rounds):
    fn()  # warm caches / first dispatch
    t0 = time.perf_counter()
    for _ in range(rounds):
        fn()
    return (time.perf_counter() - t0) / rounds


def single_process(args):
    import jax
    import jax.numpy as jnp
    import optax

    import bluefog_tpu as bf
    from bluefog_tpu import topology as topo

    bf.init(lambda: topo.ExponentialTwoGraph(max(2, bf_world())))
    n = bf.size()
    P = args.elements
    mb = P * 4 / 1e6
    x = np.random.RandomState(0).randn(n, P).astype(np.float32)
    out = {"n": n, "elements": P, "mb_per_rank": mb}
    print(f"window store: {n} ranks, {mb:.1f} MB/rank fused buffer")

    assert bf.win_create(x, "bench")
    edges = sum(len(bf.out_neighbor_ranks(r)) for r in range(n))

    t = _timeit(lambda: bf.win_put(x, "bench"), args.rounds)
    out["win_put_s"] = t
    print(f"win_put   (all {edges} edges): {t*1e3:8.1f} ms "
          f"({edges * mb / t / 1e3:6.2f} GB/s aggregate)")

    t = _timeit(lambda: bf.win_accumulate(x, "bench"), args.rounds)
    out["win_accumulate_s"] = t
    print(f"win_accumulate               : {t*1e3:8.1f} ms")

    t = _timeit(lambda: bf.win_update("bench"), args.rounds)
    out["win_update_s"] = t
    print(f"win_update (combine)         : {t*1e3:8.1f} ms")

    t = _timeit(lambda: bf.win_update_then_collect("bench"), args.rounds)
    out["win_update_then_collect_s"] = t
    print(f"win_update_then_collect      : {t*1e3:8.1f} ms")

    # Overlap window: nonblocking dispatch returns in microseconds; the put
    # runs on the worker pool while the caller computes.
    t0 = time.perf_counter()
    h = bf.win_put_nonblocking(x, "bench")
    t_dispatch = time.perf_counter() - t0
    bf.win_wait(h)
    out["dispatch_s"] = t_dispatch
    print(f"nonblocking dispatch latency : {t_dispatch*1e6:8.1f} us "
          f"(put completes on the worker pool)")
    bf.win_free("bench")

    # Device<->host staging: the only on-chip cost of the window family.
    xd = jnp.asarray(x[0])
    jax.block_until_ready(xd)
    t = _timeit(lambda: np.asarray(jax.device_get(xd)), args.rounds)
    out["device_to_host_s"] = t
    print(f"device->host ({mb:.0f} MB)      : {t*1e3:8.1f} ms "
          f"({mb / t / 1e3:6.2f} GB/s)")
    t = _timeit(
        lambda: jax.block_until_ready(jax.device_put(x[0])), args.rounds)
    out["host_to_device_s"] = t
    print(f"host->device ({mb:.0f} MB)      : {t*1e3:8.1f} ms "
          f"({mb / t / 1e3:6.2f} GB/s)")

    # DP-7 async optimizer vs DP-3 synchronous on the same tiny model.
    D = args.model_dim
    params = {"w": jnp.asarray(
        np.random.RandomState(1).randn(n, D, 1).astype(np.float32))}
    grads = jax.tree.map(jnp.zeros_like, params)
    for name, opt in [
            ("DP-7 win_put ", bf.optim.DistributedWinPutOptimizer(
                optax.sgd(0.01))),
            ("DP-7 overlap ", bf.optim.DistributedWinPutOptimizer(
                optax.sgd(0.01), window_prefix="winput_ov", overlap=True)),
            ("DP-3 sync nbr", bf.optim.DistributedNeighborAllreduceOptimizer(
                optax.sgd(0.01)))]:
        state = opt.init(params)

        def step(params=params, state=state, opt=opt):
            p, s = opt.step(params, grads, state)
            jax.block_until_ready(jax.tree_util.tree_leaves(p)[0])
            return p, s
        t = _timeit(step, args.rounds)
        out[f"opt_{name.strip().replace(' ', '_')}_s"] = t
        print(f"{name} step ({D}-param model): {t*1e3:8.2f} ms")
        if hasattr(opt, "free"):
            opt.free()
    return out


def bf_world() -> int:
    import jax
    return len(jax.devices())


_MP_CHILD = "_WINBENCH_CHILD"


def multiproc_child(args):
    # bfrun launches us by script path, so sys.path[0] is examples/ — add
    # the repo root for the package import.
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import jax
    if os.environ.get("BFTPU_LOCAL_DEVICES"):
        # Virtual-mesh mode: site hooks may pin another platform via
        # jax.config, which overrides the JAX_PLATFORMS env bfrun sets.
        jax.config.update("jax_platforms", "cpu")

    import bluefog_tpu as bf
    from bluefog_tpu import topology as topo

    bf.init_distributed(lambda: topo.RingGraph(bf_world()))
    n = bf.size()
    P = args.elements
    mb = P * 4 / 1e6
    owned = bf.owned_ranks()
    owned_layout = os.environ.get("BFTPU_BENCH_OWNED") == "1"
    if owned_layout:
        # Owned-rows layout: the caller-side array is (owned, P), not
        # (n, P) — at large n the host working set stays O(owned).
        x = np.random.RandomState(0).randn(len(owned), P).astype(np.float32)
        assert bf.win_create(x, "mp", zero_init=True)
    else:
        x = np.random.RandomState(0).randn(n, P).astype(np.float32)
        assert bf.win_create(x, "mp")
    # Cross-process edges: with 2 procs on a ring every rank has one
    # in-neighbor owned by the peer (and one local).
    my = jax.process_index()
    bf.win_fence()
    t0 = time.perf_counter()
    for _ in range(args.rounds):
        bf.win_put(x, "mp")
    bf.win_fence()  # all puts applied at their targets
    dt = (time.perf_counter() - t0) / args.rounds
    # Ring over 2 procs: each process sends its owned ranks' rows along 2
    # edges each; half the edges cross the process boundary.
    edges_out = sum(len(bf.out_neighbor_ranks(r)) for r in owned)
    cross = sum(1 for r in owned for t_ in bf.out_neighbor_ranks(r)
                if t_ not in owned)
    comp = os.environ.get("BLUEFOG_TPU_WIN_COMPRESSION", "none")
    wire_mb = mb * (0.5 if comp == "bf16" else 1.0)
    layout = "owned" if owned_layout else "rank-major"
    host_mb = x.nbytes / 1e6
    print(f"proc{my}: win_put round {dt*1e3:.1f} ms "
          f"({edges_out} edges, {cross} cross-process, "
          f"{cross * wire_mb / dt / 1e3:.2f} GB/s DCN payload, "
          f"compression={comp}, layout={layout}, "
          f"caller array {host_mb:.0f} MB)", flush=True)
    bf.win_free("mp")


def multiproc_parent(args):
    here = os.path.abspath(__file__)
    for comp, owned in (("none", "0"), ("bf16", "0"), ("none", "1")):
        env = dict(os.environ, BLUEFOG_TPU_WIN_COMPRESSION=comp,
                   BFTPU_BENCH_OWNED=owned)
        env[_MP_CHILD] = "1"
        out = subprocess.run(
            [sys.executable, "-m", "bluefog_tpu.run", "-np", "2",
             "--devices-per-proc", "2", sys.executable, here,
             "--elements", str(args.elements), "--rounds", str(args.rounds)],
            env=env, capture_output=True, text=True, timeout=600)
        if out.returncode != 0:
            print(out.stdout)
            print(out.stderr[-2000:], file=sys.stderr)
            raise SystemExit(out.returncode)
        for line in out.stdout.splitlines():
            if line.startswith("proc"):
                print(line)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--elements", type=int, default=25_557_032,
                    help="elements per rank row (default: ResNet-50 params)")
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--model-dim", type=int, default=1024)
    ap.add_argument("--multiproc", action="store_true",
                    help="measure cross-process DCN edges under bfrun -np 2")
    ap.add_argument("--json", action="store_true",
                    help="print a JSON summary line at the end")
    args = ap.parse_args()
    if os.environ.get(_MP_CHILD):
        multiproc_child(args)
        return
    if args.multiproc:
        multiproc_parent(args)
        return
    out = single_process(args)
    if args.json:
        print(json.dumps(out))


if __name__ == "__main__":
    main()
